"""One fleet replica: a `PagedDecodeServer` owned by one serving
thread.

Single-writer discipline (the same split disagg/ingest.py runs): ALL
server state — the pool, the block tables, the radix cache — is
touched exclusively by this replica's serving thread. The decode step
DONATES the pool buffers, so a reader on any other thread can observe
an invalidated buffer mid-tick; anything that must read or mutate
server state from outside (the router's block export/import during a
migration) is posted to the `ops` queue and executed by the loop
between ticks. The front-end threads only ever touch the admission
queue (owned by AdmissionController), this replica's ops queue, and
the obs gauges — all designed for cross-thread use.

Loop shape, every iteration:

    drain ops -> pop admissions while the server has room -> _admit ->
    _tick (if anything is seated) -> harvest finished requests ->
    publish a digest advertisement IF the radix generation moved ->
    refresh load gauges

Replica death (any exception out of the loop, including injected test
failures): in-flight requests — already submitted to the dead server,
their KV unrecoverable — fail loudly through `on_fail` with a
`ReplicaDeadError`; requests still parked in the admission queue were
never touched and are the front-end's to re-route (`on_dead`
callback). The dead replica stops advertising and its gauges zero, so
the router stops picking it.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Any, Callable

from defer_tpu.runtime.paged import PagedDecodeServer


class ReplicaDeadError(Exception):
    """A request failed because its replica died mid-flight. Carries
    the replica index and the root cause."""

    def __init__(self, replica: int, cause: BaseException | str):
        self.replica = replica
        self.cause = cause
        super().__init__(f"replica {replica} died: {cause}")


class ThreadReplica:
    """Default in-process replica (the fleet twin of disagg/api.py's
    `_thread_worker_spawner`). A `spawn_replica` hook can substitute
    anything exposing the same surface: `start/close/call/
    inject_failure`, `dead`, `hold_admissions`, and `srv`."""

    def __init__(
        self,
        idx: int,
        make_server: Callable[[int], PagedDecodeServer],
        controller: Any,
        board: Any,
        obs: Any,
        *,
        on_done: Callable[[Any, Any], None],
        on_fail: Callable[[Any, BaseException], None],
        on_dead: Callable[[int, BaseException], None],
    ):
        self.idx = idx
        # make_server(idx) returns this replica's server already
        # placed on its device slice (fleet/api.py documents the
        # replica <-> devices contract) — the spawner never picks
        # devices itself.
        self.srv = make_server(idx)
        self.controller = controller
        self.board = board
        self.obs = obs
        self.on_done = on_done
        self.on_fail = on_fail
        self.on_dead = on_dead
        self.ops: "queue_mod.Queue[tuple]" = queue_mod.Queue()
        self.dead: BaseException | None = None
        # Test seams: hold_admissions keeps the loop ticking seated
        # work while never popping the inbox (builds real queue
        # backlog); inject_failure raises inside the loop on its next
        # iteration (replica-death path without monkeypatching).
        self.hold_admissions = False
        self._fail: BaseException | None = None
        self._stop = threading.Event()
        self._gid_of: dict[int, Any] = {}  # rid -> gid, this replica
        self._advert_gen = -1
        self._thread = threading.Thread(
            target=self._loop, name=f"fleet-replica-{idx}", daemon=True
        )

    # -- front-end surface (any thread) -----------------------------------

    def start(self) -> None:
        self._thread.start()

    def close(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
        if self.srv._spill is not None:
            # Settle the host spill tier's drain thread so its gauges
            # (and any caller reading stored_bytes) see a final value.
            self.srv._spill.close()

    # analysis: domain(any) test seam — one pointer store, read-and-cleared by the loop; tearing is impossible and loss is acceptable
    def inject_failure(self, exc: BaseException) -> None:
        self._fail = exc

    def call(self, fn: Callable[[PagedDecodeServer], Any],
             timeout: float = 30.0) -> Any:
        """Run `fn(srv)` ON the serving thread and return its result —
        the only sanctioned way to touch server state from outside
        (module docstring). Raises ReplicaDeadError if the replica is
        (or goes) dead, TimeoutError if the loop never picks it up."""
        if self.dead is not None:
            raise ReplicaDeadError(self.idx, self.dead)
        done = threading.Event()
        box: dict[str, Any] = {}
        self.ops.put((fn, done, box))
        if not done.wait(timeout):
            raise TimeoutError(
                f"replica {self.idx} op not serviced in {timeout}s"
            )
        if "exc" in box:
            raise box["exc"]
        return box["val"]

    @property
    def inflight_gids(self) -> list:
        return list(self._gid_of.values())

    # -- serving thread ----------------------------------------------------

    def _drain_ops(self) -> None:
        while True:
            try:
                fn, done, box = self.ops.get_nowait()
            except queue_mod.Empty:
                return
            try:
                box["val"] = fn(self.srv)
            except BaseException as e:  # op errors go to the caller
                box["exc"] = e
            finally:
                done.set()

    def _take(self, req: Any) -> None:
        try:
            rid = self.srv.submit(
                req.prompt,
                req.steps,
                sampling=req.sampling,
                stop=req.stop,
            )
        except Exception as e:
            # A single unserveable request (e.g. larger than the whole
            # pool) fails ITSELF, not the replica.
            self.on_fail(req.gid, e)
            return
        self._gid_of[rid] = req.gid

    def _room(self) -> bool:
        """Pop the inbox only while the server can actually use more
        work (pending + seated < max_batch): requests beyond that wait
        in the admission queue where their wait is measured and
        sheddable, instead of hiding in an unbounded server-side list."""
        srv = self.srv
        seated = sum(1 for s in srv.slots if s is not None)
        return len(srv.pending) + seated < srv.B

    def _harvest(self) -> None:
        srv = self.srv
        for rid in list(self._gid_of):
            if rid in srv.done:
                self.on_done(self._gid_of.pop(rid), srv.done.pop(rid))

    def _publish(self) -> None:
        srv = self.srv
        gen = srv.radix.generation if srv.radix is not None else 0
        if gen == self._advert_gen:
            return  # one int compare — the advertisement fast path
        # Snapshot under the radix lock, publish OUTSIDE it (the board
        # has its own lock): the advert_lock fixture pair pins this
        # ordering as the analysis lock-discipline contract.
        gen, digests = srv.resident_digests()
        self.board.publish(self.idx, gen, digests)
        self._advert_gen = gen

    def _gauges(self) -> None:
        srv = self.srv
        seated = sum(1 for s in srv.slots if s is not None)
        self.obs.inflight[self.idx].set(
            len(srv.pending) + len(srv.pending_prefilled) + seated
        )
        headroom = len(srv.free)
        if srv.radix is not None:
            headroom += len(srv.radix.lru)  # parked = evictable
        self.obs.pool_free[self.idx].set(headroom)

    # analysis: domain(serving) the replica's loop IS its serving thread — all srv state is owned here, outside callers go through call()
    def _loop(self) -> None:
        srv = self.srv
        try:
            self._publish()
            self._gauges()
            while not self._stop.is_set():
                self._drain_ops()
                if self._fail is not None:
                    exc, self._fail = self._fail, None
                    raise exc
                progressed = False
                if not self.hold_admissions:
                    while self._room():
                        item = self.controller.try_pop(self.idx)
                        if item is None:
                            break
                        self._take(item)
                        progressed = True
                srv._admit()
                if any(s is not None for s in srv.slots):
                    srv._tick()
                    progressed = True
                self._harvest()
                self._publish()
                self._gauges()
                if progressed or srv.pending:
                    continue
                # Idle: park on the inbox briefly instead of spinning
                # the admit loop hot (disagg/api.py's idle yield).
                if self.hold_admissions:
                    time.sleep(1e-3)
                else:
                    item = self.controller.try_pop(self.idx, timeout=1e-3)
                    if item is not None:
                        self._take(item)
        except BaseException as e:
            self.dead = e
            # Fail queued ops (their callers are blocked on events).
            while True:
                try:
                    _, done, box = self.ops.get_nowait()
                except queue_mod.Empty:
                    break
                box["exc"] = ReplicaDeadError(self.idx, e)
                done.set()
            # In-flight requests die with the server; queued ones are
            # the front-end's to re-route.
            for gid in self._gid_of.values():
                self.on_fail(gid, ReplicaDeadError(self.idx, e))
            self._gid_of.clear()
            if srv._draft is not None:
                # Draft lanes hold per-slot K/V for the dead requests;
                # clear them with the pool so a post-mortem reader (or
                # a spawner that recycles the server object) never
                # sees stale draft state for requests that failed.
                srv._draft.release_all()
            self.obs.inflight[self.idx].set(0)
            self.obs.pool_free[self.idx].set(0)
            self.on_dead(self.idx, e)
