"""`serve_fleet()`: N paged decode replicas behind one front door.

The front-end owns the three cooperating pieces: the router
(fleet/router.py — prefix-aware placement over replica digest
advertisements), the admission controller (fleet/admission.py —
bounded queues + SLO shedding), and the replicas (fleet/replica.py —
one `PagedDecodeServer` per serving thread). `serve_fleet` keeps the
`serve_paged` contract — (outputs in submission order, stats) — and at
`n_replicas=1` with default knobs is token-identical to it: one
replica, nothing to route, unbounded queue, no SLO, so every request
takes the same `submit -> admit -> tick` path on the same server
class.

Replica placement defaults to in-process threads; pass
`spawn_replica(idx, make_server, controller, board, obs, *, on_done,
on_fail, on_dead)` returning a ThreadReplica-shaped object to place
replicas elsewhere (the `spawn_worker=` pattern from disagg/api.py).

Replica <-> devices contract: `make_server` takes the replica index
and returns that replica's server ALREADY PLACED — the spawner never
touches jax devices itself, it only decides where the thread/process
runs. The in-process default partitions `jax.devices()` (or the
`devices=` list) disjointly: replica i gets device `devs[i % len]`
when `model_axis_size` is None, or the next `model_axis_size`-device
slice as its own `{"model": m}` mesh (tensor-parallel serving,
runtime/paged.py `mesh=`) — wrapping around when replicas outnumber
device slices, so oversubscription shares devices rather than
stacking every replica on device 0.

Failure semantics: a dead replica fails its in-flight requests with
`ReplicaDeadError` (their KV died with the pool — silently re-running
them would hide a real outage), re-routes its still-queued requests to
surviving replicas, and drops out of the routing set. Shedding raises
`ShedError` from `submit()` — admission rejections are synchronous and
typed, never a hang.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

import jax

from defer_tpu.disagg.wire import PrefixPayload
from defer_tpu.fleet.admission import AdmissionController, ShedError
from defer_tpu.fleet.replica import ReplicaDeadError, ThreadReplica
from defer_tpu.fleet.router import AdvertisementBoard, PrefixRouter
from defer_tpu.obs.serving import FleetMetrics, FleetStats, ServerStats
from defer_tpu.runtime.paged import PagedDecodeServer
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class _FleetRequest:
    gid: int
    prompt: Any
    steps: int
    sampling: Any = None
    stop: Any = None


class FleetFrontend:
    """Construct replicas, route, admit, await. One instance per
    serving session; `close()` stops the replica threads."""

    def __init__(
        self,
        dec: Any,
        params: dict,
        *,
        n_replicas: int = 1,
        num_blocks: int,
        block_size: int = 16,
        max_batch: int = 4,
        eos_id: int | None = None,
        prefix_cache: bool = False,
        attention: str = "gathered",
        kv_dtype: str = "fp",
        spill_bytes: int = 0,
        decode_window: int = 1,
        spec_k: int = 0,
        spec_draft: Any = None,
        spec_params: dict | None = None,
        policy: str = "prefix",
        slo_s: float | None = None,
        max_queue: int = 0,
        enqueue_wait_s: float = 0.05,
        migrate: bool = True,
        migrate_gap: int = 4,
        spawn_replica: Any = None,
        model_axis_size: int | None = None,
        devices: list | None = None,
        constraints: dict | None = None,
    ):
        if n_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {n_replicas}")
        self.n_replicas = n_replicas
        self.block_size = block_size
        self.policy = policy
        self.obs = FleetMetrics(n_replicas)
        # The obs registry is process-global and instruments are
        # shared by (name, labels): zero the per-replica gauges up
        # front so a previous fleet's parting values can't steer this
        # run's first routing decisions.
        for i in range(n_replicas):
            self.obs.queue_depth[i].set(0)
            self.obs.inflight[i].set(0)
            self.obs.pool_free[i].set(0)
        self.controller = AdmissionController(
            n_replicas,
            self.obs,
            max_queue=max_queue,
            slo_s=slo_s,
            enqueue_wait_s=enqueue_wait_s,
        )
        self.board = AdvertisementBoard(n_replicas)
        self.router = PrefixRouter(
            self.board,
            self.obs,
            policy=policy,
            migrate=migrate,
            migrate_gap=migrate_gap,
        )
        self.alive = [True] * n_replicas

        # Disjoint device partitioning (module docstring): replica i's
        # placement comes from its index, wrap-around when replicas
        # outnumber devices/slices. model_axis_size turns each replica
        # into an m-chip tensor-parallel mesh.
        devs = list(devices) if devices is not None else jax.devices()
        if model_axis_size is not None and model_axis_size < 1:
            raise ValueError(
                f"model_axis_size must be >= 1, got {model_axis_size}"
            )

        def _placement(i: int) -> dict:
            if model_axis_size is None:
                return {"device": devs[i % len(devs)]}
            from defer_tpu.parallel.mesh import make_mesh

            m = model_axis_size
            chunk = [devs[(i * m + j) % len(devs)] for j in range(m)]
            return {"mesh": make_mesh({"model": m}, chunk)}

        def make_server(i: int) -> PagedDecodeServer:
            return PagedDecodeServer(
                dec,
                params,
                num_blocks=num_blocks,
                block_size=block_size,
                max_batch=max_batch,
                eos_id=eos_id,
                prefix_cache=prefix_cache,
                attention=attention,
                kv_dtype=kv_dtype,
                spill_bytes=spill_bytes,
                decode_window=decode_window,
                spec_k=spec_k,
                spec_draft=spec_draft,
                spec_params=spec_params,
                constraints=constraints,
                **_placement(i),
            )

        spawn = spawn_replica or ThreadReplica
        self.replicas = [
            spawn(
                i,
                make_server,
                self.controller,
                self.board,
                self.obs,
                on_done=self._complete,
                on_fail=self._fail,
                on_dead=self._on_dead,
            )
            for i in range(n_replicas)
        ]
        self._lock = threading.RLock()
        self._results: dict[int, dict] = {}
        self._next_gid = 0
        self.routed = {r: 0 for r in FleetMetrics.ROUTE_REASONS}
        self.shed = {r: 0 for r in FleetMetrics.SHED_REASONS}
        self.migrated_blocks = 0
        for r in self.replicas:
            r.start()

    # -- result plumbing (called from replica threads) ---------------------

    def _complete(self, gid: int, tokens: Any) -> None:
        slot = self._results.get(gid)
        if slot is None:
            return
        slot["val"] = tokens
        slot["event"].set()

    def _fail(self, gid: int, exc: BaseException) -> None:
        slot = self._results.get(gid)
        if slot is None:
            return
        slot["exc"] = exc
        slot["event"].set()

    def _on_dead(self, idx: int, exc: BaseException) -> None:
        """Replica-death protocol: drop it from routing, then re-route
        everything still parked in its admission queue (never touched
        by the dead server). Runs on the dying replica's thread."""
        log.warning("fleet replica %d died: %s", idx, exc)
        with self._lock:
            self.alive[idx] = False
            queued = self.controller.drain(idx)
        for req in queued:
            try:
                self._route_and_admit(req)
            except (ShedError, RuntimeError, ReplicaDeadError) as e:
                self._fail(req.gid, e)

    # -- routing -----------------------------------------------------------

    def _do_migrate(self, decision) -> bool:
        """Ship the decided prefix chain source -> target as a
        disagg/wire PrefixPayload (the importer recomputes the chained
        digests from the payload's token bytes). Both ends run on
        their own serving threads via replica ops. False = anything
        went stale or broke; the caller downgrades to fallback."""
        src = self.replicas[decision.source]
        dst = self.replicas[decision.replica]
        keys = decision.keys
        try:
            exported = src.call(
                lambda srv: srv.export_prefix_blocks(keys)
            )
            if exported is None:
                return False  # evicted since the advertisement
            toks, k, v = exported
            payload = PrefixPayload(toks=toks, k=k, v=v)
            n = dst.call(
                lambda srv: srv.import_prefix_blocks(
                    payload.toks, payload.k, payload.v
                )
            )
        except (ReplicaDeadError, TimeoutError) as e:
            log.warning("prefix migration failed: %s", e)
            return False
        if n:
            self.obs.migrated_blocks.inc(n)
            self.migrated_blocks += n
        return True

    def _route_and_admit(self, req: _FleetRequest) -> None:
        with self._lock:
            t0 = int(req.prompt.shape[1])
            decision = self.router.route(
                req.prompt,
                t0 // self.block_size,
                self.block_size,
                self.alive,
            )
            if decision.reason == "migrate":
                if not self._do_migrate(decision):
                    decision.reason = "fallback"
            self.obs.routed[decision.reason].inc()
            self.routed[decision.reason] += 1
            try:
                self.controller.admit(decision.replica, req)
            except ShedError as e:
                self.shed[e.reason] = self.shed.get(e.reason, 0) + 1
                raise

    # -- public API --------------------------------------------------------

    def submit(
        self,
        prompt_ids: Any,
        num_steps: int,
        *,
        sampling: Any = None,
        stop: Any = None,
    ) -> int:
        """Route + enqueue one request; returns a fleet-wide id for
        `result()`. Raises ShedError synchronously when admission
        rejects it (the future is cleaned up — a shed request can
        never be waited on into a hang)."""
        if prompt_ids.ndim != 2 or prompt_ids.shape[0] != 1:
            raise ValueError("submit one request at a time ([1, T])")
        with self._lock:
            gid = self._next_gid
            self._next_gid += 1
        self._results[gid] = {"event": threading.Event()}
        req = _FleetRequest(gid, prompt_ids, num_steps, sampling, stop)
        try:
            self._route_and_admit(req)
        except ShedError:
            del self._results[gid]
            raise
        return gid

    def result(self, gid: int, timeout: float | None = None) -> Any:
        """Block until request `gid` finishes; returns its [1, T]
        token array or raises the request's typed failure
        (ReplicaDeadError et al)."""
        slot = self._results.get(gid)
        if slot is None:
            raise KeyError(f"unknown or shed request {gid}")
        if not slot["event"].wait(timeout):
            raise TimeoutError(f"request {gid} not done in {timeout}s")
        del self._results[gid]
        if "exc" in slot:
            raise slot["exc"]
        return slot["val"]

    def close(self) -> None:
        for r in self.replicas:
            r.close()

    def stats(self) -> FleetStats:
        """Fleet-level snapshot plus per-replica ServerStats (the same
        fields `serve_paged` reports), replica-index order; dead
        replicas report `dead` with the cause."""
        per = []
        for r in self.replicas:
            srv = r.srv
            per.append(
                ServerStats.snapshot(
                    srv.obs.registry,
                    ticks=srv.ticks,
                    attention=srv.attention,
                    peak_blocks=srv.blocks_peak,
                    pool_blocks=srv.num_blocks - 1,
                    block_size=srv.bs,
                    decode_window=srv.decode_window,
                    host_dispatches=srv.dispatches,
                    cached_blocks=(
                        srv.radix.cached_blocks
                        if srv.radix is not None
                        else 0
                    ),
                    prefill_tokens_saved=srv.prefill_tokens_saved,
                    prefill_budget=srv.prefill_budget,
                    prefill_stall_ticks=srv.prefill_stall_ticks_n,
                    mixed_ticks=srv.mixed_ticks_n,
                    mixed_prefill_tokens=srv.mixed_prefill_tokens_n,
                    decode_stall_fraction=(
                        srv.decode_stall_fraction_last
                    ),
                    mesh_shape=srv.mesh_label,
                    kv_dtype=srv.kv_dtype,
                    pool_bytes=srv.pool_bytes,
                    spec_k=srv.spec_k,
                    spec_rounds=srv.spec_rounds_n,
                    spec_proposed=srv.spec_proposed_n,
                    spec_accepted=srv.spec_accepted_n,
                    spec_acceptance=(
                        srv.spec_accepted_n / srv.spec_proposed_n
                        if srv.spec_proposed_n
                        else 0.0
                    ),
                    spec_draft_tokens=srv.spec_draft_tokens_n,
                    spilled_blocks=(
                        srv._spill.stored_blocks
                        if srv._spill is not None
                        else 0
                    ),
                    spill_hits=srv.spill_hits_n,
                    constrained_tokens=srv.constrained_tokens_n,
                    constraint_dead_ends=srv.constraint_dead_ends_n,
                    dead=str(r.dead) if r.dead is not None else None,
                )
            )
        return FleetStats.snapshot(
            self.obs.registry,
            n_replicas=self.n_replicas,
            policy=self.policy,
            routed=dict(self.routed),
            shed=dict(self.shed),
            migrated_blocks=self.migrated_blocks,
            replicas=per,
        )


def serve_fleet(
    dec: Any,
    params: dict,
    requests: list[tuple[jax.Array, int]],
    *,
    n_replicas: int = 1,
    num_blocks: int,
    block_size: int = 16,
    max_batch: int = 4,
    eos_id: int | None = None,
    prefix_cache: bool = False,
    attention: str = "gathered",
    kv_dtype: str = "fp",
    spill_bytes: int = 0,
    decode_window: int = 1,
    spec_k: int = 0,
    spec_draft: Any = None,
    spec_params: dict | None = None,
    sampling: list | None = None,
    stop: list | None = None,
    policy: str = "prefix",
    slo_s: float | None = None,
    max_queue: int = 0,
    migrate: bool = True,
    migrate_gap: int = 4,
    spawn_replica: Any = None,
    model_axis_size: int | None = None,
    devices: list | None = None,
    result_timeout_s: float = 600.0,
    constraints: dict | None = None,
) -> tuple[list[jax.Array], dict]:
    """One-shot fleet serving; same contract as `serve_paged` (outputs
    in submission order + stats) over `n_replicas` paged servers, each
    sized `num_blocks`/`max_batch` on its own. Default knobs shed
    nothing (unbounded queues, no SLO) — overload policy is opt-in via
    `slo_s`/`max_queue`, and a ShedError then propagates to the
    caller. Returns FleetStats: routing-reason and shed counts,
    migrated block totals, and per-replica ServerStats.

    Placement: replicas partition `jax.devices()` (or `devices=`)
    disjointly, one device each by default; `model_axis_size=m` gives
    each replica its own m-device "model" mesh and serves it
    tensor-parallel (FleetFrontend docstring has the contract).

    `kv_dtype`/`spill_bytes` apply to every replica's pool
    (PagedDecodeServer docstring). Prefix-block migration between
    replicas is dtype-transparent: export dequantizes to the wire's
    compute dtype and the importing replica's pool requantizes on
    landing, so mixed-pool fleets still migrate.

    `spec_k`/`spec_draft`/`spec_params` turn on speculative decoding
    on EVERY replica (each gets its own DraftLanes over its own
    devices). Migration composes for free: only TARGET prefix blocks
    ship between pools, and the admitting replica's draft lane always
    re-prefills the full prompt locally (radix hits are a pool
    concept the draft does not share), so a migrated admission
    speculates exactly like a local one. A dying replica's draft
    lanes are torn down with its pool (`DraftLanes.release_all` in
    the replica loop's failure path).

    `constraints={name: TokenDFA}` registers compiled grammars on
    EVERY replica (defer_tpu/constrain/): each replica stacks its own
    device copy of the DFA tables, so a request opting in via
    `SamplingParams(constraint="name")` decodes constrained on
    whichever replica the router picks (migration ships prefix
    blocks, never sampler state — a request's DFA walk lives and dies
    on its admitting replica). Per-replica ServerStats then carry
    `constrained_tokens` / `constraint_dead_ends`."""
    fe = FleetFrontend(
        dec,
        params,
        n_replicas=n_replicas,
        num_blocks=num_blocks,
        block_size=block_size,
        max_batch=max_batch,
        eos_id=eos_id,
        prefix_cache=prefix_cache,
        attention=attention,
        kv_dtype=kv_dtype,
        spill_bytes=spill_bytes,
        decode_window=decode_window,
        spec_k=spec_k,
        spec_draft=spec_draft,
        spec_params=spec_params,
        policy=policy,
        slo_s=slo_s,
        max_queue=max_queue,
        migrate=migrate,
        migrate_gap=migrate_gap,
        spawn_replica=spawn_replica,
        model_axis_size=model_axis_size,
        devices=devices,
        constraints=constraints,
    )
    samps = sampling or [None] * len(requests)
    stops = stop or [None] * len(requests)
    if len(samps) != len(requests) or len(stops) != len(requests):
        raise ValueError(
            "sampling/stop must have one entry per request when given"
        )
    try:
        gids = [
            fe.submit(p, s, sampling=sp, stop=st)
            for (p, s), sp, st in zip(requests, samps, stops)
        ]
        outs = [fe.result(g, timeout=result_timeout_s) for g in gids]
    finally:
        fe.close()
    return outs, fe.stats()
