"""Prefix-aware request routing: cache locality as the placement
signal.

DEFER's front node dispatches work across compute nodes; TensorFlow's
placer assigns ops to the device whose state they read. This router is
the serving version of both ideas: a `PrefixBlockCache` keys blocks by
EXACT chained blake2b token-ancestry digests, so "which replica
already holds this prompt's prefix" is a set lookup, not a heuristic.
Each replica advertises its resident digest set (a cheap generation-
gated snapshot, `PagedDecodeServer.resident_digests`); the router
chains each incoming prompt's digests with the SAME hash and walks
them against the advertisements to find the deepest resident run.

Decision ladder (reasons match FleetMetrics.ROUTE_REASONS):

  * `prefix`   — a live replica holds a non-empty leading run of the
                 prompt's blocks and isn't badly overloaded: route to
                 it; admission revives the parked blocks for free.
  * `migrate`  — the deepest holder is overloaded relative to the
                 least-loaded replica by more than `migrate_gap`:
                 ship the parked chain (disagg/wire.py PrefixPayload)
                 to the least-loaded replica and route there — the
                 prefix travels to the capacity instead of the request
                 queueing behind the hot replica.
  * `load`     — no replica holds any of the prompt's blocks: route
                 least-loaded.
  * `fallback` — a prefix exists somewhere but is unusable (holder
                 dead, or migration disabled/failed): least-loaded,
                 counted separately because it is exactly the routing
                 quality the advertisement freshness budget buys.

Load is read from the fleet obs gauges the replicas maintain
(`queue_depth + inflight`, pool headroom as the tie-breaker, replica
index as the deterministic final tie-break), so routing decisions are
measured, not guessed — and reproducible under equal load.

Advertisement discipline: replicas snapshot their digest set UNDER the
radix lock and publish OUTSIDE it (the board takes its own lock). A
publish inside the radix lock would serialize admission against
whatever the advertisement fanout does — the exact anti-pattern the
analysis lock-discipline rule (and its advert_lock fixture pair)
flags.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any

import numpy as np

from defer_tpu.runtime.paged import PrefixBlockCache


def chain_digests(tokens: Any, n_full: int, bs: int) -> list[bytes]:
    """The routing-side twin of PrefixBlockCache.walk's key pass:
    chained digests for the prompt's leading `n_full` full blocks,
    byte-identical to what the replica caches register under (same
    `_hash`, same int64 token encoding) — the router and the caches
    must agree bit-for-bit or every lookup silently misses."""
    # analysis: ignore[host-sync-in-hot-loop] routing hashes prompt
    # token bytes on the host — one transfer per REQUEST at admission,
    # not per decode tick
    flat = np.asarray(tokens).reshape(-1)[: n_full * bs].astype(np.int64)
    keys: list[bytes] = []
    prev = b""
    for j in range(n_full):
        prev = PrefixBlockCache._hash(
            prev, flat[j * bs : (j + 1) * bs].tobytes()
        )
        keys.append(prev)
    return keys


class AdvertisementBoard:
    """Last-published digest snapshot per replica, with its generation
    and publish timestamp. Publishers (replica serving threads) and
    the reading router contend only on this board's own lock, never on
    any replica's radix lock."""

    def __init__(self, n_replicas: int):
        self._lock = threading.Lock()
        self._adverts: list[tuple[int, frozenset, float]] = [
            (-1, frozenset(), time.monotonic())
            for _ in range(n_replicas)
        ]

    def publish(
        self, idx: int, generation: int, digests: frozenset
    ) -> None:
        with self._lock:
            self._adverts[idx] = (generation, digests, time.monotonic())

    def snapshot(self) -> list[tuple[int, frozenset, float]]:
        with self._lock:
            return list(self._adverts)


@dataclasses.dataclass
class RouteDecision:
    """Where one request goes and why. `keys` is the chained-digest
    run backing a prefix/migrate decision (what to export); `source`
    is the overloaded holder a `migrate` ships from."""

    replica: int
    reason: str
    depth: int = 0
    keys: list = dataclasses.field(default_factory=list)
    source: int | None = None


class PrefixRouter:
    """Stateless-per-request routing over the advertisement board.

    `policy="prefix"` is the real router; `policy="round_robin"`
    ignores the advertisements entirely (deterministic rotation over
    live replicas) and exists as the control arm every prefix-aware
    claim is measured against (scripts/bench_fleet.py)."""

    def __init__(
        self,
        board: AdvertisementBoard,
        obs: Any,
        *,
        policy: str = "prefix",
        migrate: bool = True,
        migrate_gap: int = 4,
    ):
        if policy not in ("prefix", "round_robin"):
            raise ValueError(
                f"policy must be 'prefix' or 'round_robin', got "
                f"{policy!r}"
            )
        self.board = board
        self.obs = obs
        self.policy = policy
        self.migrate = migrate
        self.migrate_gap = migrate_gap
        self._rr = 0

    def _load(self, idx: int) -> tuple:
        """Deterministic load score, smaller = less loaded: queued +
        in-flight work first, then the LEAST pool headroom last
        (negated free blocks), then the replica index so equal load
        breaks ties identically on every run."""
        return (
            self.obs.queue_depth[idx].value
            + self.obs.inflight[idx].value,
            -self.obs.pool_free[idx].value,
            idx,
        )

    def route(
        self,
        tokens: Any,
        n_full: int,
        bs: int,
        alive: list[bool],
    ) -> RouteDecision:
        """One placement decision. `alive[i]` False excludes replica i
        as a TARGET while its (stale) advertisement still counts as "a
        prefix existed" — a dead holder routes `fallback`, not `load`,
        so the death shows up in the routing mix instead of vanishing."""
        if not any(alive):
            raise RuntimeError("no live replicas to route to")
        adverts = self.board.snapshot()
        now = time.monotonic()
        self.obs.advert_age.set(
            max(
                now - t
                for i, (_, _, t) in enumerate(adverts)
                if alive[i]
            )
        )
        if self.policy == "round_robin":
            n = len(alive)
            for _ in range(n):
                idx = self._rr % n
                self._rr += 1
                if alive[idx]:
                    return RouteDecision(idx, "load")
        keys = chain_digests(tokens, n_full, bs)
        best_idx, best_depth = -1, 0
        for i, (_, digests, _) in enumerate(adverts):
            depth = 0
            for key in keys:
                if key not in digests:
                    break
                depth += 1
            # Strict > : equal depth keeps the lower index, the same
            # deterministic tie-break direction as _load's final key.
            if depth > best_depth:
                best_idx, best_depth = i, depth
        least = min(
            (i for i in range(len(alive)) if alive[i]), key=self._load
        )
        if best_depth == 0:
            return RouteDecision(least, "load")
        if not alive[best_idx]:
            return RouteDecision(least, "fallback", best_depth)
        holder_load = self._load(best_idx)[0]
        least_load = self._load(least)[0]
        if (
            best_idx != least
            and holder_load - least_load >= self.migrate_gap
        ):
            if self.migrate:
                return RouteDecision(
                    least,
                    "migrate",
                    best_depth,
                    keys[:best_depth],
                    source=best_idx,
                )
            return RouteDecision(least, "fallback", best_depth)
        return RouteDecision(
            best_idx, "prefix", best_depth, keys[:best_depth]
        )
