"""defer_tpu.fleet — prefix-aware routing + admission control over N
replica decode servers.

The DEFER front node, serving-shaped: one entry point fans requests
over multiple `PagedDecodeServer` replicas, placing each request where
its KV state already lives (the radix cache's chained token-ancestry
digests make "who holds this prompt's prefix" an exact lookup), and
degrading overload into typed rejections instead of collapsed tail
latency:

  * `router`    — digest advertisements, the prefix/migrate/load/
                  fallback decision ladder, deterministic tie-breaks
  * `admission` — bounded per-replica queues, SLO-deadline waits
                  (runtime/batching.py::Deadline), `ShedError`
  * `replica`   — one server per serving thread, single-writer ops
                  queue, `ReplicaDeadError` failure semantics
  * `api`       — `serve_fleet()` / `FleetFrontend`, token-identical
                  to `serve_paged` at n_replicas=1

See ARCHITECTURE.md "Fleet serving".
"""

from defer_tpu.fleet.admission import AdmissionController, ShedError
from defer_tpu.fleet.api import FleetFrontend, serve_fleet
from defer_tpu.fleet.replica import ReplicaDeadError, ThreadReplica
from defer_tpu.fleet.router import (
    AdvertisementBoard,
    PrefixRouter,
    RouteDecision,
    chain_digests,
)

__all__ = [
    "AdmissionController",
    "AdvertisementBoard",
    "FleetFrontend",
    "PrefixRouter",
    "ReplicaDeadError",
    "RouteDecision",
    "ShedError",
    "ThreadReplica",
    "chain_digests",
    "serve_fleet",
]
