"""Admission control for the fleet front-end: bounded per-replica
queues plus SLO-driven load shedding.

The failure mode this file exists for: under overload an UNBOUNDED
queue converts excess arrival rate into unbounded queue wait — every
request eventually completes, and every request's latency is ruined.
Admission control inverts that: the queue depth is bounded, the
enqueue wait is deadline-bounded (`runtime/batching.py::Deadline`, the
same monotonic remaining-budget machinery the batch gatherer's flush
SLO runs on), and once the ROLLING queue-wait p99 exceeds the
configured SLO new arrivals are rejected with a typed `ShedError`
instead of being queued into certain SLO violation. Shedding keeps the
p99 of the traffic that IS admitted bounded — overload degrades into
explicit rejections, not collapsed tail latency.

The p99 estimate is a rolling window (a deque of the most recent
waits), NOT the cumulative obs histogram: a cumulative estimate can
never recover after a burst (old samples are never forgotten), so the
shedder would latch open. The obs histogram still records every wait
for dashboards; only the shedding DECISION reads the window.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from collections import deque
from typing import Any

from defer_tpu.runtime.batching import Deadline


class ShedError(Exception):
    """Typed admission rejection. `reason` is one of
    FleetMetrics.SHED_REASONS: "queue_full" (the bounded queue never
    drained within the enqueue deadline) or "slo" (the rolling
    queue-wait p99 already exceeds the SLO — queueing more work would
    only deepen the violation)."""

    def __init__(
        self,
        reason: str,
        replica: int,
        *,
        queue_depth: int = 0,
        wait_p99_s: float | None = None,
        slo_s: float | None = None,
    ):
        self.reason = reason
        self.replica = replica
        self.queue_depth = queue_depth
        self.wait_p99_s = wait_p99_s
        self.slo_s = slo_s
        detail = f"queue_depth={queue_depth}"
        if wait_p99_s is not None:
            detail += f", queue-wait p99 {wait_p99_s * 1e3:.1f}ms"
        if slo_s is not None:
            detail += f" vs SLO {slo_s * 1e3:.1f}ms"
        super().__init__(
            f"request shed ({reason}) at replica {replica}: {detail}"
        )


class AdmissionController:
    """Bounded FIFO admission queue per replica.

    Producer side (`admit`, router thread): sheds on SLO violation,
    then blocks for queue space under a `Deadline` and sheds on
    expiry. Consumer side (`try_pop`/`pop`, each replica's serving
    thread): records the realized queue wait into both the obs
    histogram and the rolling shedding window.

    `max_queue=0` means unbounded (and `queue_full` unreachable);
    `slo_s=None` disables SLO shedding. Both defaults keep
    `serve_fleet` shed-free so the single-replica token-identity
    contract needs no carve-outs."""

    def __init__(
        self,
        n_replicas: int,
        obs: Any,
        *,
        max_queue: int = 0,
        slo_s: float | None = None,
        enqueue_wait_s: float = 0.05,
        window: int = 512,
    ):
        if n_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {n_replicas}")
        self.obs = obs
        self.max_queue = max_queue
        self.slo_s = slo_s
        self.enqueue_wait_s = enqueue_wait_s
        self._queues: list["queue_mod.Queue[tuple[float, Any]]"] = [
            queue_mod.Queue(maxsize=max_queue) for _ in range(n_replicas)
        ]
        self._waits: list[deque] = [
            deque(maxlen=window) for _ in range(n_replicas)
        ]
        self._wait_locks = [threading.Lock() for _ in range(n_replicas)]

    def wait_p99(self, idx: int) -> float:
        """Rolling p99 of the most recent realized queue waits for one
        replica (0.0 while the window is empty)."""
        with self._wait_locks[idx]:
            waits = sorted(self._waits[idx])
        if not waits:
            return 0.0
        return waits[min(int(0.99 * len(waits)), len(waits) - 1)]

    def depth(self, idx: int) -> int:
        return self._queues[idx].qsize()

    def admit(self, idx: int, item: Any) -> None:
        """Enqueue `item` for replica `idx` or raise ShedError. The
        enqueue timestamp rides the queue entry so the consumer's
        pickup measures the full queued interval."""
        if self.slo_s is not None:
            p99 = self.wait_p99(idx)
            if p99 > self.slo_s:
                self.obs.shed["slo"].inc()
                raise ShedError(
                    "slo",
                    idx,
                    queue_depth=self.depth(idx),
                    wait_p99_s=p99,
                    slo_s=self.slo_s,
                )
        q = self._queues[idx]
        if self.max_queue == 0:
            q.put((time.monotonic(), item))
        else:
            dl = Deadline(self.enqueue_wait_s)
            while True:
                try:
                    q.put(
                        (time.monotonic(), item),
                        timeout=max(dl.remaining(), 1e-4),
                    )
                    break
                except queue_mod.Full:
                    if dl.expired():
                        self.obs.shed["queue_full"].inc()
                        raise ShedError(
                            "queue_full",
                            idx,
                            queue_depth=self.depth(idx),
                            wait_p99_s=self.wait_p99(idx) or None,
                            slo_s=self.slo_s,
                        ) from None
        self.obs.queue_depth[idx].set(q.qsize())

    def try_pop(self, idx: int, timeout: float | None = None) -> Any:
        """Consumer pickup: the queued item, or None when empty after
        `timeout` (None = non-blocking). Records the realized wait."""
        q = self._queues[idx]
        try:
            if timeout is None:
                t_enq, item = q.get_nowait()
            else:
                t_enq, item = q.get(timeout=timeout)
        except queue_mod.Empty:
            return None
        wait = time.monotonic() - t_enq
        self.obs.queue_wait[idx].observe(wait)
        with self._wait_locks[idx]:
            self._waits[idx].append(wait)
        self.obs.queue_depth[idx].set(q.qsize())
        return item

    def drain(self, idx: int) -> list[Any]:
        """Empty replica `idx`'s queue (replica-death requeue path):
        returns the queued items, oldest first, without recording
        waits — these requests were never picked up."""
        out = []
        q = self._queues[idx]
        while True:
            try:
                out.append(q.get_nowait()[1])
            except queue_mod.Empty:
                break
        self.obs.queue_depth[idx].set(0)
        return out

    def record_wait(self, idx: int, wait_s: float) -> None:
        """Seed the rolling window directly (tests drive the SLO
        shedder without a real queue backlog)."""
        self.obs.queue_wait[idx].observe(wait_s)
        with self._wait_locks[idx]:
            self._waits[idx].append(wait_s)
