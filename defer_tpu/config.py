"""Configuration for defer_tpu.

The reference hard-codes every knob (dispatcher IP at reference
src/dispatcher.py:25, node IPs at src/test.py:20, ports at src/node.py:18,
chunk size at src/dispatcher.py:26, queue sizes at src/test.py:44-45).
Here everything is an explicit dataclass; topology comes from the JAX
runtime rather than IP lists.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax.numpy as jnp


@dataclasses.dataclass
class DeferConfig:
    """All knobs for a pipelined inference run.

    Attributes:
      compute_dtype: dtype activations/params are cast to for compute.
        bfloat16 keeps matmuls/convs on the MXU at full rate.
      param_dtype: dtype parameters are STORED in on device. None (the
        default) stores them in compute_dtype — for bf16 inference that
        removes a full fp32->bf16 cast pass over the weights on every
        microbatch (~10% ResNet50 throughput on v5e). Set an explicit
        dtype (e.g. jnp.float32) to keep higher-precision storage.
      max_inflight: microbatches allowed in flight before the host blocks
        on the oldest result — the backpressure analogue of the
        reference's bounded queues (reference src/test.py:44,
        src/node.py:139).
      probe_every: during run_defer, measure per-stage latency
        (synchronously, draining first) every N microbatches and stash
        it on DEFER.last_stage_latencies; 0 disables probing.
      donate_activations: donate inter-stage activation buffers to XLA.
      collective_timeout_s: watchdog timeout for a stage/transfer that
        never completes (the reference has no failure detection at all;
        a dead node hangs it forever — reference src/node.py:30-31).
      redispatch_attempts: on a stage failure during run_defer, probe
        device health and rebuild the pipeline on the healthy devices
        up to this many times, retrying the failed microbatch (elastic
        recovery; results in flight at failure time may be lost and the
        retried input re-runs from stage 0). 0 = fail fast.
      dynamic_batch_size: during run_defer, coalesce up to this many
        adjacent input-queue items into ONE device batch (outputs are
        split back per item, order preserved). The reference streams
        batch-1 frames (reference src/test.py:52-54); the TPU is ~50x
        faster at batch 256 than batch 1, so serving loops should
        batch. 1 disables (default).
      batch_wait_s: with dynamic batching, how long to wait for more
        items after a batch's first item arrives — the latency SLO the
        batcher trades against device efficiency.
    """

    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = None

    @property
    def storage_dtype(self) -> Any:
        """The dtype parameters are actually stored in on device."""
        return self.param_dtype if self.param_dtype is not None else (
            self.compute_dtype
        )
    max_inflight: int = 32
    probe_every: int = 0
    donate_activations: bool = True
    collective_timeout_s: float = 120.0
    redispatch_attempts: int = 1
    dynamic_batch_size: int = 1
    batch_wait_s: float = 0.005

    def replace(self, **kw: Any) -> "DeferConfig":
        return dataclasses.replace(self, **kw)


def normalize_cuts(
    cuts: Sequence[str | Sequence[str]] | str | None,
) -> tuple[str | tuple[str, ...], ...]:
    """None -> (), "a" -> ("a",), and sequences pass through with list
    bundles frozen to tuples (multi-tensor boundaries).

    Note a top-level sequence is always a *list of cuts*: a single
    bundle must be wrapped — pass [("h2", "h1")], not ("h2", "h1")
    (the latter reads as two single-tensor cuts).
    """
    if cuts is None:
        return ()
    if isinstance(cuts, str):
        return (cuts,)
    return tuple(
        tuple(c) if isinstance(c, (list, tuple)) else c for c in cuts
    )
