"""LoRA fine-tuning over the SPMD transformer stack.

Beyond-reference capability (the reference is inference-only): low-rank
adapters make fine-tuning a large frozen model cheap — only the
[in, r] x [r, out] factor pairs train, so the optimizer state (the Adam
moments that normally double a model's HBM cost) shrinks from O(model)
to O(adapters), and the base weights can stay in bf16/int8 untouched.

TPU-first shape of the design:

  * adapter factors live INSIDE the stacked param tree
    (``{target}:a`` / ``{target}:b``, init_stack), so the same
    `lax.scan` block body, circular-ppermute pipeline, and Megatron
    tensor-parallel shardings serve adapted and plain stacks — no
    second code path. Column-parallel targets shard ``b`` over tp;
    row-parallel targets shard ``a`` and ride the block's existing
    psum (the low-rank path is linear, so the same collective closes
    both partial sums).
  * training splits the tree by suffix: `jax.value_and_grad` runs
    ONLY over the adapter leaves (plus the task head), so backward
    never materializes base-weight gradients, and the optimizer state
    covers adapters only.
  * serving merges: ``merge_lora`` folds ``w + scale * a @ b`` into
    the base weights and drops the factor keys, producing a plain
    stack any consumer (SpmdBert, GptDecoder KV-cache serving,
    checkpointing) runs at exactly base-model cost.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from defer_tpu.parallel.train import TrainState, make_classifier_params


def is_lora_key(name: str) -> bool:
    return name.endswith(":a") or name.endswith(":b")


def split_lora(params: dict) -> tuple[dict, dict]:
    """Partition a param tree into (frozen base, trainable adapters).

    Adapter leaves are the ``{target}:a`` / ``{target}:b`` entries of
    the "stack" sub-dict; everything else (embeddings, norms, base
    weights, pooler) is base. Both halves keep the same nesting so
    ``combine_lora`` is a pure dict merge.
    """
    base = {k: v for k, v in params.items() if k != "stack"}
    stack = params.get("stack", {})
    base["stack"] = {k: v for k, v in stack.items() if not is_lora_key(k)}
    lora = {"stack": {k: v for k, v in stack.items() if is_lora_key(k)}}
    return base, lora


def combine_lora(base: dict, lora: dict) -> dict:
    """Inverse of split_lora: one tree the stack forward consumes."""
    out = {k: v for k, v in base.items() if k != "stack"}
    out["stack"] = {**base.get("stack", {}), **lora.get("stack", {})}
    return out


def merge_lora(params: dict, cfg) -> dict:
    """Fold every adapter into its base weight: w <- w + scale * a @ b.

    Returns a plain (adapter-free) tree — same keys a lora_rank=0
    init_stack would produce — so serving, checkpointing, and the
    KV-cache decoder run the fine-tuned model at base-model cost.
    The contraction is over the trailing two axes, so both the flat
    [L, ...] init_stack layout and the [S, L/S, ...] stage-stacked
    layout (spmd_pipeline.stack_for_stages) merge unchanged.
    """
    scale = cfg.lora_scale
    stack = dict(params.get("stack", {}))
    for key in [k for k in stack if k.endswith(":a")]:
        target = key[:-2]
        a = stack.pop(key)
        b = stack.pop(f"{target}:b")
        w = stack[target]
        delta = jnp.einsum(
            "...ir,...ro->...io",
            a.astype(jnp.float32),
            b.astype(jnp.float32),
        )
        stack[target] = (w.astype(jnp.float32) + scale * delta).astype(
            w.dtype
        )
    out = {k: v for k, v in params.items() if k != "stack"}
    out["stack"] = stack
    return out


def stack_adapters(params: dict, adapter_trees: list, cfg) -> dict:
    """Attach N fine-tuned adapter trees for MULTI-LoRA serving.

    Each tree is a split_lora adapter half ({"stack": {"wq:a": [L, in,
    r], ...}}) from the same lora config. The banks stack on a new
    adapter axis — {t}:a [L, A+1, in, r] / {t}:b [L, A+1, r, out] —
    with id 0 reserved as the ZERO adapter (base-model behavior), so a
    serving batch mixes tenants and plain-base requests freely
    (GptDecoder._block gathers each row's bank by its slot's adapter
    id; runtime/decode_server.py::submit(adapter_id=...)).

    cfg.lora_scale is folded into the stored b factors here — serving
    then needs no scale plumbing, and the per-row delta is exactly the
    merge_lora delta for that adapter id.
    """
    if not adapter_trees:
        raise ValueError("no adapter trees")
    keys = sorted(
        k for k in adapter_trees[0]["stack"] if k.endswith(":a")
    )
    if not keys:
        raise ValueError("adapter trees carry no ':a' factors")
    for tree in adapter_trees[1:]:
        if sorted(
            k for k in tree["stack"] if k.endswith(":a")
        ) != keys:
            raise ValueError(
                "adapter trees disagree on targets — all tenants must "
                "come from the same lora config"
            )
    scale = cfg.lora_scale
    stack = dict(params["stack"])
    for key in keys:
        t = key[:-2]
        a = jnp.stack(
            [tree["stack"][key] for tree in adapter_trees], axis=1
        )  # [L, A, in, r]
        b = (
            jnp.stack(
                [tree["stack"][f"{t}:b"] for tree in adapter_trees],
                axis=1,
            )
            * scale
        )
        stack[key] = jnp.concatenate(
            [jnp.zeros_like(a[:, :1]), a], axis=1
        )
        stack[f"{t}:b"] = jnp.concatenate(
            [jnp.zeros_like(b[:, :1]), b], axis=1
        )
    return {**params, "stack": stack}


def adapter_bank_info(params: dict) -> int | None:
    """Multi-LoRA detection shared by the serving stacks: None when
    `params` carries no adapter factors; the bank count A+1 when
    stacked banks ([L, A+1, in, r]) are attached; a loud ValueError
    for unmerged 3-D training factors (which would otherwise be
    misread as banks)."""
    stack = params.get("stack", {})
    bank = next((v for k, v in stack.items() if k.endswith(":a")), None)
    if bank is None:
        return None
    if bank.ndim != 4:
        raise ValueError(
            f"params carry unmerged LoRA factors (shape {bank.shape}): "
            "merge_lora them for single-adapter serving, or "
            "stack_adapters for multi-tenant banks [L, A, in, r]"
        )
    return int(bank.shape[1])


def make_lora_train_step(
    sb,
    optimizer: optax.GradientTransformation,
    *,
    num_classes: int,
) -> tuple[
    Callable[[jax.Array], tuple[TrainState, dict]],
    Callable[
        [TrainState, dict, jax.Array, jax.Array], tuple[TrainState, jax.Array]
    ],
]:
    """LoRA counterpart of train.make_train_step.

    Returns (init_state, train_step):

      * ``init_state(rng) -> (state, base)``: ``state.params`` holds
        ONLY the trainable leaves (adapter factors + classifier head)
        and the optimizer state covers just those; ``base`` is the
        frozen tree (reuse a pretrained checkpoint here).
      * ``train_step(state, base, ids [M, B, S], labels [M, B])``:
        grads flow through the full pipelined forward but only with
        respect to the trainable leaves — base-weight gradients are
        never built. ``base`` is passed (not closed over) so one
        compiled step serves any checkpoint of the same shape.

    sb.cfg.lora_rank must be > 0 (init_stack then creates the factor
    keys this splits on).
    """
    if not sb.cfg.lora_rank:
        raise ValueError(
            "make_lora_train_step needs cfg.lora_rank > 0 — with no "
            "adapter keys in the stack there is nothing to train"
        )
    forward = sb.make_step()

    def loss_fn(trainable: dict, base: dict, ids, labels):
        params = combine_lora(base, trainable)
        pooled = forward(params, ids)  # [M, B, D]
        logits = (
            pooled.astype(jnp.float32) @ trainable["cls_w"]
            + trainable["cls_b"]
        )
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        )
        return losses.mean()

    def init_state(rng: jax.Array):
        base, lora = split_lora(sb.init(rng))
        trainable = dict(lora)
        trainable.update(
            make_classifier_params(
                jax.random.fold_in(rng, 17), sb, num_classes
            )
        )
        state = TrainState(
            params=trainable,
            opt_state=optimizer.init(trainable),
            step=jnp.zeros((), jnp.int32),
        )
        return state, base

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, base: dict, ids, labels):
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, base, ids, labels
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return init_state, train_step
