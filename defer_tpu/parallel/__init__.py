from defer_tpu.parallel.data_parallel import (
    ReplicatedPipeline,
    ShardedInference,
)
from defer_tpu.parallel.mesh import (
    describe_topology,
    make_mesh,
    pipeline_devices,
)
from defer_tpu.parallel.pipeline import Pipeline

__all__ = [
    "Pipeline",
    "ReplicatedPipeline",
    "ShardedInference",
    "describe_topology",
    "make_mesh",
    "pipeline_devices",
]
