from defer_tpu.parallel.mesh import (
    describe_topology,
    make_mesh,
    pipeline_devices,
)
from defer_tpu.parallel.pipeline import Pipeline

__all__ = ["Pipeline", "describe_topology", "make_mesh", "pipeline_devices"]
