"""Device-pinned pipeline runtime for heterogeneous stage chains.

This is the TPU-native replacement for the reference's entire data plane:
its per-node recv/compute/send thread pairs (reference src/node.py:97-133),
bounded hand-off queues (src/node.py:139), TCP framing
(src/node_state.py:43-101) and ZFP+LZ4 codec (src/node.py:93-96) all
collapse into:

  * one jit-compiled XLA program per stage, pinned to its own TPU core
    (parameters committed there once at load, like the reference's
    one-time weight dispatch, src/dispatcher.py:47-63);
  * `jax.device_put` core-to-core activation transfers that ride ICI —
    no serialization, no compression, no sockets;
  * JAX's asynchronous dispatch as the pipelining engine: the host
    enqueues microbatch t on stage 0 while stage k still computes
    microbatch t-k, so all stages overlap exactly as the reference's
    thread pipeline does, minus the Python in the hot loop.

Backpressure (the reference's bounded queues, src/test.py:44) becomes a
cap on in-flight microbatches enforced by blocking on the oldest result.
"""

from __future__ import annotations

import time
from typing import Any, Iterable, Iterator, Sequence

import jax
import jax.numpy as jnp

from defer_tpu.config import DeferConfig
from defer_tpu.graph.ir import Graph, GraphParams
from defer_tpu.graph.partition import StageGraph, stage_params
from defer_tpu.obs.metrics import get_registry
from defer_tpu.utils.logging import get_logger
from defer_tpu.utils.profiling import annotate
from defer_tpu.utils.sync import Retirer, hard_sync

log = get_logger(__name__)


def cast_params_to_storage(params: Any, config: DeferConfig) -> Any:
    """Cast floating-point param leaves to config.storage_dtype once at
    placement time — casting inside every stage call would cost an
    extra HBM pass per microbatch (~10% ResNet50 throughput on v5e)."""
    sd = config.storage_dtype
    if not jnp.issubdtype(sd, jnp.floating):
        return params
    return jax.tree_util.tree_map(
        lambda a: a.astype(sd)
        if jnp.issubdtype(a.dtype, jnp.floating)
        else a,
        params,
    )


def probe_latency(fn: Any, *args: Any, iters: int = 10) -> dict[str, Any]:
    """Synchronous latency sample for one compiled callable — the
    timing core `Pipeline.probe_stage_latencies` reports per stage,
    extracted so other stage chains (the paged server's pp layer
    probe) measure with identical methodology. Runs one untimed call
    first (compile), then `iters` hard-synced calls for the p50, then
    one amortized window (dispatch `iters`, one barrier)."""
    hard_sync(fn(*args))  # ensure compiled
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        hard_sync(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(iters)]
    hard_sync(outs[-1])
    amortized = (time.perf_counter() - t0) / iters
    return {
        "p50_s": times[len(times) // 2],
        "p99_s": times[int(len(times) * 0.99)] if len(times) >= 100 else None,
        "max_s": times[-1],
        "min_s": times[0],
        "amortized_s": amortized,
    }


def balance_stage_cuts(costs: Sequence[float], num_stages: int) -> list[int]:
    """Contiguous min-max partition of per-layer costs into
    `num_stages` stages: returns the stage START indices
    (cuts[0] == 0), chosen so the most expensive stage is as cheap as
    possible. Exact O(L^2 * S) DP — layer counts are tens, not
    thousands. Every stage is non-empty, so num_stages must not
    exceed len(costs)."""
    L = len(costs)
    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    if num_stages > L:
        raise ValueError(
            f"cannot split {L} layers into {num_stages} non-empty "
            "stages"
        )
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + float(c))

    def span(i: int, j: int) -> float:
        return prefix[j] - prefix[i]

    # best[s][j] = minimal max-stage-cost splitting costs[:j] into s
    # stages; cut[s][j] = start of the last stage in that optimum.
    INF = float("inf")
    best = [[INF] * (L + 1) for _ in range(num_stages + 1)]
    cut = [[0] * (L + 1) for _ in range(num_stages + 1)]
    best[0][0] = 0.0
    for s in range(1, num_stages + 1):
        for j in range(s, L + 1):
            for i in range(s - 1, j):
                cand = max(best[s - 1][i], span(i, j))
                if cand < best[s][j]:
                    best[s][j] = cand
                    cut[s][j] = i
    starts: list[int] = []
    j = L
    for s in range(num_stages, 0, -1):
        i = cut[s][j]
        starts.append(i)
        j = i
    starts.reverse()
    return starts


class StreamMeasure:
    """Shared warmup/throughput for anything with __call__ + stream
    (Pipeline, ShardedInference, ReplicatedPipeline) — one definition
    of the measurement protocol, the analogue of the reference's timed
    result counting (reference src/test.py:33-41)."""

    def warmup(self, x: Any) -> jax.Array:
        """Compile (first XLA compile is slow; do it before timing —
        the analogue of the reference's settling sleep, reference
        src/dispatcher.py:126, but deterministic)."""
        out = self(x)
        hard_sync(out)
        return out

    def throughput(
        self, x: Any, num_microbatches: int = 256
    ) -> dict[str, float]:
        self.warmup(x)
        t0 = time.perf_counter()
        n = 0
        last = None
        for out in self.stream(x for _ in range(num_microbatches)):
            last = out
            n += 1
        # A true completion barrier: device program order guarantees the
        # last output retires after every earlier same-program execution
        # (replicated runtimes warm every replica above, and their last
        # round covers each replica's tail).
        hard_sync(last)
        dt = time.perf_counter() - t0
        batch = int(x.shape[0]) if hasattr(x, "shape") and x.ndim > 0 else 1
        return {
            "microbatches": n,
            "seconds": dt,
            "microbatches_per_sec": n / dt,
            "items_per_sec": n * batch / dt,
        }


class Pipeline(StreamMeasure):
    """A chain of jit-compiled stages, each pinned to one device."""

    def __init__(
        self,
        stages: Sequence[Graph | StageGraph],
        params: GraphParams,
        devices: Sequence[jax.Device],
        config: DeferConfig | None = None,
    ):
        if len(devices) != len(stages):
            raise ValueError(
                f"{len(stages)} stages need {len(stages)} devices, "
                f"got {len(devices)}"
            )
        self.config = config or DeferConfig()
        self.stages = list(stages)
        self.devices = list(devices)
        cd = self.config.compute_dtype

        self.stage_params: list[Any] = []
        self.stage_fns: list[Any] = []
        # Non-donating twins, used where an input must survive the call
        # (latency probing re-times the same activation repeatedly).
        self._plain_fns: list[Any] = []
        for i, (stage, dev) in enumerate(zip(self.stages, self.devices)):
            sp = jax.device_put(
                cast_params_to_storage(stage_params(params, stage), self.config),
                dev,
            )
            self.stage_params.append(sp)

            def stage_apply(p, x, _stage=stage, _cd=cd):
                # Integer inputs (token ids) must keep their dtype.
                # x may be a tuple (multi-tensor boundary).
                x = jax.tree_util.tree_map(
                    lambda a: a.astype(_cd)
                    if jnp.issubdtype(a.dtype, jnp.floating)
                    else a,
                    x,
                )
                return _stage.apply(p, x)

            # Stage 0's input is caller-owned (device_put of an array
            # already on the device aliases it) — never donate that.
            # Later stages consume pipeline-owned transfer buffers.
            donate = (1,) if self.config.donate_activations and i > 0 else ()
            # analysis: ignore[fresh-closure-jit] one jit per STAGE at
            # construction, held in stage_fns for the pipeline's
            # lifetime — never rebuilt per call
            self.stage_fns.append(jax.jit(stage_apply, donate_argnums=donate))
            # analysis: ignore[fresh-closure-jit] same: built once,
            # cached on the instance
            self._plain_fns.append(jax.jit(stage_apply))
        # One shared counter across every Pipeline (incl. the ones a
        # ReplicatedPipeline builds per replica): total microbatches
        # dispatched process-wide.
        self._obs_microbatches = get_registry().counter(
            "defer_pipeline_microbatches_total",
            "Microbatches dispatched through a stage chain",
        )

    @property
    def num_stages(self) -> int:
        return len(self.stages)

    # -- execution -------------------------------------------------------

    @staticmethod
    def _place(x: Any, dev: jax.Device) -> Any:
        """device_put only when an array isn't already resident on
        `dev` — a redundant device_put of a host-uncommitted array
        re-transfers the whole buffer from the host. Tree-aware for
        multi-tensor boundary tuples."""
        return jax.tree_util.tree_map(
            lambda a: a
            if isinstance(a, jax.Array) and a.sharding.device_set == {dev}
            else jax.device_put(a, dev),
            x,
        )

    def __call__(self, x: jax.Array) -> jax.Array:
        """Push one microbatch through the chain (async — the returned
        array is a future; block_until_ready() to wait)."""
        self._obs_microbatches.inc()
        h = self._place(x, self.devices[0])
        for i, (fn, p) in enumerate(zip(self.stage_fns, self.stage_params)):
            with annotate(f"defer:stage{i}"):
                if i > 0:
                    h = self._place(h, self.devices[i])
                h = fn(p, h)
        return h

    # Uniform submission point for stream loops: replicated runtimes
    # override this to fan successive microbatches across replicas.
    submit = __call__

    def stream(
        self,
        inputs: Iterable[Any],
        *,
        max_inflight: int | None = None,
    ) -> Iterator[jax.Array]:
        """Stream microbatches through the pipeline with bounded
        in-flight depth; yields outputs in order.

        The analogue of the reference's steady-state hot loop
        (SURVEY.md §3.3): feed thread + per-node threads + result
        server, here a single loop over async dispatches.
        """
        depth = max_inflight or self.config.max_inflight
        retirer = Retirer(depth)
        for x in inputs:
            # Backpressure: Retirer emits the known-ready prefix for
            # free and, at depth, takes one batched barrier on the
            # middle of the window — never waits per item; completion
            # notification can cost ~ms each (utils/sync.py).
            yield from retirer.add(self(x))
        yield from retirer.flush()

    # -- measurement (warmup/throughput come from StreamMeasure) ---------

    def probe_stage_latencies(
        self, x: Any, iters: int = 10
    ) -> list[dict[str, Any]]:
        """Per-stage latency in seconds, measured synchronously
        (BASELINE.json's metric asks for per-stage p50). Run outside the
        streaming loop so probing doesn't break overlap. `p99_s` is only
        reported when iters >= 100 — below that the 99th percentile of
        the sample IS its max, so `max_s` carries it honestly instead."""
        h = self._place(x, self.devices[0])
        results = []
        for i, (fn, p) in enumerate(zip(self._plain_fns, self.stage_params)):
            if i > 0:
                h = self._place(h, self.devices[i])
                hard_sync(h)
            # Amortized half excludes the per-call host sync round
            # trip, which on tunneled transports dwarfs the stage
            # itself (probe_latency docstring has the methodology).
            sample = probe_latency(fn, p, h, iters=iters)
            amortized = sample["amortized_s"]
            results.append(
                {"stage": i, "device": str(self.devices[i]), **sample}
            )
            # Cold path: registry lookup per probe is fine here.
            reg = get_registry()
            labels = {"stage": str(i)}
            reg.gauge(
                "defer_stage_amortized_seconds",
                "Amortized per-microbatch stage time (last probe)",
                labels,
            ).set(amortized)
            reg.gauge(
                "defer_stage_p50_seconds",
                "Synchronous p50 stage latency (last probe)",
                labels,
            ).set(sample["p50_s"])
            h = fn(p, h)
        return results
