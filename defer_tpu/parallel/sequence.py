"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context support is first-class in this framework even though the
reference has none (SURVEY.md §5: "no attention, no sequence axis
anywhere in src/"). Two standard strategies, both pure collectives over
a `seq` mesh axis so XLA schedules the transfers on ICI:

* **Ring attention** (`ring_attention`): Q stays resident; K/V blocks
  rotate one hop per step with `lax.ppermute` while each device folds
  the visiting block into a streaming-softmax accumulator (the same
  online recurrence as the Pallas flash kernel, lifted across chips).
  Memory per device is O(S_local · D); the S×S score matrix never
  exists. Compute for step t overlaps the ppermute for step t+1.

* **Ulysses** (`ulysses_attention`): two `lax.all_to_all`s re-shard
  from sequence-sharded to head-sharded and back, so attention itself
  runs unsharded on a head subset. Cheaper collectives for moderate S;
  requires num_heads % seq_axis_size == 0.

Both operate on already-projected (B, H, S_local, Dh) tensors inside
`shard_map` and compose with tensor parallelism (heads are first split
over the tp axis, then handled per-strategy over the seq axis).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from defer_tpu.ops.pallas_attention import _MASK_VALUE


def _block_scores(q, k, scale):
    return (
        lax.dot_general(
            q,
            k,
            (((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        )
        * scale
    )  # (B, H, Sq, Sk)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Ring attention on (B, H, S_local, Dh) shards, inside shard_map.

    The global sequence is the concatenation of every device's shard in
    axis-index order. Returns the local shard of the attention output.
    """
    n = lax.axis_size(axis_name)  # static: mesh shape is trace-time
    idx = lax.axis_index(axis_name)
    # K/V travel backward around the ring (device i receives from i+1),
    # so after t steps device i holds the block of device (i + t) % n.
    perm = [(i, (i - 1) % n) for i in range(n)]
    s_local = q.shape[2]
    scale = q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32)

    # Fresh zeros would be device-invariant; the accumulators must be
    # varying over every manual axis q is varying over (seq here, plus
    # e.g. the pipeline's stage axis when nested) — deriving them from
    # qf inherits exactly that type, and XLA folds the arithmetic away.
    zero_row = qf.sum(axis=-1) * 0.0  # (B, H, S_local) f32
    m, l, acc = zero_row + _MASK_VALUE, zero_row, qf * 0.0
    k_cur, v_cur = k, v
    # Unrolled over the (static, small) ring size so the last iteration
    # skips its rotation — a fori_loop body would pay one wasted ICI hop
    # of the full K/V shards per attention call. XLA overlaps each
    # ppermute with the previous block's matmuls.
    for t in range(n):
        src = (idx + t) % n  # global block index k_cur/v_cur came from
        s = _block_scores(qf, k_cur.astype(jnp.float32), scale)
        if causal:
            q_pos = idx * s_local + lax.broadcasted_iota(
                jnp.int32, s.shape, 2
            )
            k_pos = src * s_local + lax.broadcasted_iota(
                jnp.int32, s.shape, 3
            )
            s = jnp.where(q_pos >= k_pos, s, _MASK_VALUE)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + lax.dot_general(
            p,
            v_cur.astype(jnp.float32),
            (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32,
        )
        m = m_new
        if t < n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    return (acc / l[..., None]).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Ulysses attention on (B, H, S_local, Dh) shards, inside shard_map.

    all_to_all to (B, H/n, S_global, Dh), plain attention on the full
    sequence for the local head group, all_to_all back.
    """
    from defer_tpu.ops.attention import attention_reference

    n = lax.axis_size(axis_name)
    if q.shape[1] % n:
        raise ValueError(
            f"num local heads {q.shape[1]} must divide by seq axis size {n}"
        )
    def to_heads(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = attention_reference(qh, kh, vh, causal=causal)
    return lax.all_to_all(
        out, axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def sequence_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str | None,
    strategy: str = "ring",
    causal: bool = False,
) -> jax.Array:
    """Dispatch on (B, H, S_local, Dh): ring / ulysses / local."""
    if axis_name is None:
        from defer_tpu.ops.attention import attention_reference

        return attention_reference(q, k, v, causal=causal)
    if strategy == "ring":
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal)
    if strategy == "ulysses":
        return ulysses_attention(q, k, v, axis_name=axis_name, causal=causal)
    raise ValueError(f"unknown sequence-parallel strategy {strategy!r}")


def make_sharded_attention(
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    strategy: str = "ring",
    causal: bool = False,
):
    """Jittable (q, k, v) -> out on GLOBAL (B, H, S, Dh) tensors with S
    sharded over `seq_axis` — the standalone entry point (the
    transformer stack calls `sequence_attention` directly inside its own
    shard_map instead)."""
    spec = P(None, None, seq_axis, None)

    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def attn(q, k, v):
        return sequence_attention(
            q, k, v, axis_name=seq_axis, strategy=strategy, causal=causal
        )

    return attn
