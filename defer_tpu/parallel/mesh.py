"""TPU topology discovery and mesh construction.

The reference wires its "topology" by hand: a Python list of node IPs
(reference src/test.py:20) plus a hard-coded dispatcher IP (reference
src/dispatcher.py:25), with each node told its successor's address over a
socket (reference src/dispatcher.py:54-58). Here topology comes from the
JAX runtime: `jax.devices()` enumerates the slice, and meshes are built
with `jax.sharding.Mesh` so collectives ride ICI.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def describe_topology() -> dict:
    """Human/bench-readable snapshot of the accelerator topology."""
    devs = jax.devices()
    return {
        "backend": jax.default_backend(),
        "num_devices": len(devs),
        "num_local_devices": jax.local_device_count(),
        "num_hosts": jax.process_count(),
        "device_kind": devs[0].device_kind if devs else "none",
    }


def pipeline_devices(
    num_stages: int, devices: Sequence[jax.Device] | None = None
) -> list[jax.Device]:
    """Pick one device per pipeline stage.

    With fewer devices than stages, stages wrap round-robin (the
    reference simply requires len(nodes) == len(stages) and crashes
    otherwise, reference src/dispatcher.py:49); round-robin lets an
    8-stage cut list still run on a 1- or 4-chip host, which is also how
    the single-chip benchmark exercises multi-stage overhead honestly.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if not devs:
        raise RuntimeError("no JAX devices available")
    return [devs[i % len(devs)] for i in range(num_stages)]


def make_mesh(
    axes: Mapping[str, int], devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build a named mesh, e.g. make_mesh({"data": 2, "stage": 4}).

    Axis order follows dict order; total size must match the device
    count used.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    shape = tuple(axes.values())
    n = int(np.prod(shape)) if shape else 1
    if n > len(devs):
        raise ValueError(
            f"mesh {dict(axes)} needs {n} devices, have {len(devs)}"
        )
    arr = np.array(devs[:n]).reshape(shape)
    return Mesh(arr, tuple(axes.keys()))
