"""Data-parallel inference: batch-sharded SPMD apply + replicated
pipelines.

The reference scales throughput exactly one way — deeper pipelines
(more compute nodes in the chain, reference src/dispatcher.py:47-63).
On TPU that is rarely the best mapping: a CNN's whole forward fits on
one chip, so the idiomatic way to use N chips is to shard the BATCH
over a "data" mesh axis and let XLA replicate the program (SURVEY.md §2
lists this as the natural extension the reference lacks). Two runtimes:

  * `ShardedInference` — ONE jitted program over a mesh: params
    replicated, batch sharded over the data axis. Zero host
    orchestration in the hot loop; XLA inserts any collectives. This is
    the throughput-optimal strategy when the model fits one device.
  * `ReplicatedPipeline` — R independent device-pinned pipeline
    replicas (defer_tpu.parallel.pipeline.Pipeline) fed round-robin;
    composes data parallelism with the heterogeneous stage chain when
    the model does NOT fit one device (params spread over S devices,
    R x S total). In-order output merging preserves the stream
    contract.

Both present the Pipeline surface (`__call__`, `stream`, `throughput`),
so the DEFER facade and the bench harness drive them interchangeably.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from defer_tpu.config import DeferConfig
from defer_tpu.graph.ir import Graph, GraphParams
from defer_tpu.graph.partition import StageGraph
from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.parallel.pipeline import (
    Pipeline,
    StreamMeasure,
    cast_params_to_storage,
)
from defer_tpu.utils.logging import get_logger
from defer_tpu.utils.sync import Retirer, hard_sync

log = get_logger(__name__)


class ReplicaRetirer:
    """Retirer bank for interleaved multi-replica streams.

    One Retirer per replica: the windowed-barrier trick ("sync one item,
    retire everything enqueued before it") relies on device program
    order, which only holds WITHIN one pipeline — a single shared
    Retirer over round-robin submissions would retire (and count as
    completed) items of a wedged sibling replica. Here each replica's
    items retire against its own program order, and a rotation pointer
    restores global stream order at emit time.

    Presents the Retirer surface DEFER._stream_loop drives: add /
    collect / flush / discard / ready_count.
    """

    def __init__(
        self,
        num_replicas: int,
        depth: int,
        sync: Any = hard_sync,
    ):
        # Per-replica depth: total in-flight stays within the caller's
        # max_inflight bound (it may cap activation residency, so never
        # exceed it) — but a depth-1 Retirer blocks on its windowed
        # barrier at every add(), so warn that the bank degrades to
        # synchronous per-item dispatch when the window is too small.
        per = max(1, depth // num_replicas)
        if per < 2:
            log.warning(
                "max_inflight=%d gives %d replicas a per-replica window "
                "of 1: dispatch degrades to synchronous per-item "
                "round-trips; set max_inflight >= %d to restore "
                "pipelining",
                depth,
                num_replicas,
                2 * num_replicas,
            )
        self.retirers = [Retirer(per, sync) for _ in range(num_replicas)]
        self._ready: list[list[Any]] = [[] for _ in range(num_replicas)]
        self._add_at = 0
        self._emit_at = 0

    def _drain(self) -> list[Any]:
        out = []
        n = len(self.retirers)
        while True:
            r = self._emit_at % n
            if not self._ready[r]:
                break
            out.append(self._ready[r].pop(0))
            self._emit_at += 1
        return out

    def add(self, item: Any) -> list[Any]:
        r = self._add_at % len(self.retirers)
        self._add_at += 1
        self._ready[r].extend(self.retirers[r].add(item))
        return self._drain()

    def collect(self) -> list[Any]:
        for r, ret in enumerate(self.retirers):
            self._ready[r].extend(ret.collect())
        return self._drain()

    def flush(self) -> list[Any]:
        for r, ret in enumerate(self.retirers):
            self._ready[r].extend(ret.flush())
        return self._drain()

    def discard(self) -> int:
        """Drop everything not yet emitted (in-flight and stuck-behind-
        a-gap results); returns the count, mirroring Retirer.discard."""
        n = sum(ret.discard() for ret in self.retirers)
        n += sum(len(p) for p in self._ready)
        self._ready = [[] for _ in self.retirers]
        # Re-align rotation: the stream restarts cleanly after a
        # re-dispatch with no half-emitted round.
        self._add_at = 0
        self._emit_at = 0
        return n

    def ready_count(self) -> int:
        return sum(ret.ready_count() for ret in self.retirers) + sum(
            len(p) for p in self._ready
        )

    def __len__(self) -> int:
        return sum(len(ret) for ret in self.retirers)


class ShardedInference(StreamMeasure):
    """Batch-sharded SPMD apply of a whole graph over a device mesh."""

    def __init__(
        self,
        graph: Graph,
        params: GraphParams,
        devices: Sequence[jax.Device] | Mesh | None = None,
        config: DeferConfig | None = None,
        *,
        data_axis: str = "data",
    ):
        self.config = config or DeferConfig()
        if isinstance(devices, Mesh):
            self.mesh = devices
        else:
            devs = (
                list(devices) if devices is not None else list(jax.devices())
            )
            self.mesh = make_mesh({data_axis: len(devs)}, devs)
        self.data_axis = data_axis
        self.num_shards = self.mesh.shape[data_axis]
        self.graph = graph
        cd = self.config.compute_dtype

        rep = NamedSharding(self.mesh, P())
        # Replicate params once at placement (the analogue of the
        # reference's one-time weight dispatch, src/dispatcher.py:47-63).
        self.params = jax.device_put(
            cast_params_to_storage(params, self.config), rep
        )
        self._in_sharding = NamedSharding(self.mesh, P(data_axis))

        def apply(p, x):
            if jnp.issubdtype(x.dtype, jnp.floating):
                x = x.astype(cd)
            return graph.apply(p, x)

        self._fn = jax.jit(
            apply,
            in_shardings=(rep, self._in_sharding),
            out_shardings=self._in_sharding,
        )

    def __call__(self, x: jax.Array) -> jax.Array:
        """Apply to one batch (async). The leading dim must divide by
        the data-axis size — pad at the driver if it doesn't."""
        if x.shape[0] % self.num_shards:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by {self.num_shards} "
                f"data shards — pad the batch or resize the mesh"
            )
        return self._fn(self.params, x)

    submit = __call__  # one SPMD program: no replica fan-out needed

    def stream(
        self, inputs: Iterable[Any], *, max_inflight: int | None = None
    ) -> Iterator[jax.Array]:
        depth = max_inflight or self.config.max_inflight
        retirer = Retirer(depth)
        for x in inputs:
            yield from retirer.add(self(x))
        yield from retirer.flush()


class ReplicatedPipeline(StreamMeasure):
    """R pipeline replicas over R x S devices, fed round-robin.

    Output order is the input order: replica r gets microbatches
    r, r+R, r+2R, ... and each replica is internally in-order, so
    yielding one result per replica in dispatch rotation restores the
    global stream order without any reordering buffer.
    """

    def __init__(
        self,
        stages: Sequence[Graph | StageGraph],
        params: GraphParams,
        devices: Sequence[jax.Device],
        config: DeferConfig | None = None,
        *,
        num_replicas: int | None = None,
    ):
        self.config = config or DeferConfig()
        n_stages = len(stages)
        if num_replicas is None:
            num_replicas = max(1, len(devices) // n_stages)
        if num_replicas * n_stages > len(devices):
            raise ValueError(
                f"{num_replicas} replicas x {n_stages} stages needs "
                f"{num_replicas * n_stages} devices, got {len(devices)}"
            )
        self.pipes = [
            Pipeline(
                stages,
                params,
                devices[r * n_stages : (r + 1) * n_stages],
                self.config,
            )
            for r in range(num_replicas)
        ]
        log.info(
            "replicated pipeline: %d replicas x %d stages over %d devices",
            num_replicas,
            n_stages,
            num_replicas * n_stages,
        )

    @property
    def num_replicas(self) -> int:
        return len(self.pipes)

    @property
    def num_stages(self) -> int:
        return self.pipes[0].num_stages

    def __call__(self, x: jax.Array) -> jax.Array:
        # Single-shot call: replica 0 (no fan-out to coordinate).
        return self.pipes[0](x)

    def submit(self, x: jax.Array) -> jax.Array:
        """Round-robin one microbatch to the next replica. Callers that
        submit through here (DEFER._stream_loop) retire results in
        dispatch order, which IS global stream order."""
        r = self._next_replica
        self._next_replica = (r + 1) % len(self.pipes)
        return self.pipes[r](x)

    _next_replica = 0

    def make_retirer(self, depth: int, sync: Any = hard_sync) -> ReplicaRetirer:
        """The retirer matching round-robin `submit` order (one Retirer
        per replica; see ReplicaRetirer). Stream loops that submit
        through this runtime MUST retire through this, or a wedged
        replica's unfinished work gets retired on a sibling's barrier.

        Resets the submit rotation so the retirer's internal rotation
        starts aligned; every failure path re-aligns via
        ReplicaRetirer.discard() + a fresh pipeline."""
        self._next_replica = 0
        return ReplicaRetirer(len(self.pipes), depth, sync)

    def stream(
        self, inputs: Iterable[Any], *, max_inflight: int | None = None
    ) -> Iterator[jax.Array]:
        """Round-robin dispatch with a per-replica in-flight cap."""
        depth = max_inflight or self.config.max_inflight
        retirer = self.make_retirer(depth * len(self.pipes))
        for x in inputs:
            yield from retirer.add(self.submit(x))
        yield from retirer.flush()

    def warmup(self, x: Any) -> jax.Array:
        # Every replica is its own jit/device placement — warm them all
        # (StreamMeasure.warmup would only compile replica 0).
        outs = [p(x) for p in self.pipes]
        for o in outs:
            hard_sync(o)
        return outs[0]

    def probe_stage_latencies(
        self, x: Any, iters: int = 10
    ) -> list[dict[str, float]]:
        """Per-stage latencies of replica 0 (replicas are identical
        programs on identical hardware)."""
        return self.pipes[0].probe_stage_latencies(x, iters=iters)
