"""Distributed training step over the SPMD pipeline.

The reference is inference-only (SURVEY.md §5: "nothing to checkpoint",
no training anywhere), but this framework treats training as a
first-class capability of the same SPMD machinery: ONE jitted step
computes loss and gradients *through* the ppermute pipeline (pp), the
Megatron tensor-parallel matmuls (tp), ring/Ulysses attention (sp), the
expert-parallel MoE FFN (ep) and the batch sharding (dp), then applies
an optax update — every collective inserted by XLA on ICI.

Gradients flow backward through `lax.ppermute` as the reverse permute,
so pipeline-parallel backprop needs no hand-written schedule: the scan
transpose reverses the warm-up/drain automatically.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from defer_tpu.models.bert import SpmdBert


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def make_classifier_params(
    rng: jax.Array, sb: SpmdBert, num_classes: int
) -> dict:
    """Replicated classification head on the pooled output."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    rep = NamedSharding(sb.mesh, P())
    w = jax.random.normal(rng, (sb.cfg.dim, num_classes)) * sb.cfg.dim**-0.5
    return {
        "cls_w": jax.device_put(w, rep),
        "cls_b": jax.device_put(jnp.zeros((num_classes,)), rep),
    }


def make_train_step(
    sb: SpmdBert,
    optimizer: optax.GradientTransformation,
    *,
    num_classes: int,
) -> tuple[
    Callable[[jax.Array, Any], TrainState],
    Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, jax.Array]],
]:
    """Returns (init_state, train_step).

    train_step(state, ids [M, B, S], labels [M, B]) -> (state, loss):
    microbatches stream through the pipeline, per-microbatch CLS
    classification losses are averaged, and one optimizer update is
    applied — i.e. M microbatches of gradient accumulation happen
    *inside* the pipelined program, which is exactly what keeps the
    pipeline bubble amortized during training.
    """
    forward = sb.make_step()

    def loss_fn(params, ids, labels):
        pooled = forward(params, ids)  # [M, B, D]
        logits = (
            pooled.astype(jnp.float32) @ params["cls_w"] + params["cls_b"]
        )
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        )
        return losses.mean()

    def init_state(rng: jax.Array, extra_params: dict | None = None):
        params = {**sb.init(rng)}
        params.update(
            make_classifier_params(
                jax.random.fold_in(rng, 17), sb, num_classes
            )
        )
        if extra_params:
            params.update(extra_params)
        return TrainState(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    # Donating the incoming state lets XLA alias the old params/opt-state
    # buffers for the updated ones, halving peak HBM for the train state.
    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, ids, labels):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, ids, labels)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return init_state, train_step
