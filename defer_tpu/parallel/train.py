"""Distributed training step over the SPMD pipeline.

The reference is inference-only (SURVEY.md §5: "nothing to checkpoint",
no training anywhere), but this framework treats training as a
first-class capability of the same SPMD machinery: ONE jitted step
computes loss and gradients *through* the ppermute pipeline (pp), the
Megatron tensor-parallel matmuls (tp), ring/Ulysses attention (sp), the
expert-parallel MoE FFN (ep) and the batch sharding (dp), then applies
an optax update — every collective inserted by XLA on ICI.

Gradients flow backward through `lax.ppermute` as the reverse permute,
so pipeline-parallel backprop needs no hand-written schedule: the scan
transpose reverses the warm-up/drain automatically.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from defer_tpu.models.bert import SpmdBert


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def zero1_shardings(
    opt_state: Any, params: Any, mesh, data_axis: str = "data"
) -> Any:
    """ZeRO-1 placement for optimizer state: shard every param-shaped
    moment over the data axis too.

    Adam's mu/nu normally replicate across data-parallel replicas —
    pure waste, since each replica holds identical numbers. The
    GSPMD formulation of ZeRO-1 is just sharding: give each moment
    its param's PartitionSpec plus `data_axis` on the first
    still-unsharded dimension the axis size divides. XLA then keeps
    the moments 1/dp per chip and inserts the (ICI) collectives where
    the update needs them. Numerics are untouched — it is the same
    program with different layouts.

    Works structurally: any optimizer-state subtree whose tree shape
    matches `params` (optax moments like ScaleByAdamState.mu/.nu) is
    resharded; scalars and non-matching leaves (e.g. step counts)
    stay replicated.
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    dp = mesh.shape.get(data_axis, 1)
    pstruct = jax.tree_util.tree_structure(params)
    pleaves = jax.tree_util.tree_leaves(params)

    from defer_tpu.parallel.transformer_stack import (
        first_free_divisible_dim,
    )

    def _axes_in(spec):
        out = set()
        for e in spec:
            if isinstance(e, tuple):
                out |= set(e)
            elif e is not None:
                out.add(e)
        return out

    def moment_sharding(pleaf, mleaf):
        spec = list(getattr(pleaf.sharding, "spec", P()) or ())
        spec += [None] * (mleaf.ndim - len(spec))
        # Skip when the mesh has no data axis (nothing to shard over)
        # or the param is ALREADY data-sharded (FSDP): the moment then
        # inherits that layout, which is already 1/dp per chip —
        # adding the axis twice would be an invalid sharding.
        if dp > 1 and data_axis not in _axes_in(spec):
            i = first_free_divisible_dim(spec, mleaf.shape, dp)
            if i is not None:
                spec[i] = data_axis
        return NamedSharding(mesh, P(*spec))

    rep = NamedSharding(mesh, P())

    # Walk the optimizer state one named field at a time (optax states
    # are (nested tuples of) NamedTuples whose param-shaped fields
    # mirror the param tree exactly).
    def walk(node):
        if jax.tree_util.tree_structure(node) == pstruct:
            return jax.tree_util.tree_unflatten(
                pstruct,
                jax.tree_util.tree_map(
                    moment_sharding,
                    pleaves,
                    jax.tree_util.tree_leaves(node),
                ),
            )
        if hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(walk(f) for f in node))
        if isinstance(node, (tuple, list)):
            return type(node)(walk(f) for f in node)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return rep

    return walk(opt_state)


def make_classifier_params(
    rng: jax.Array, sb: SpmdBert, num_classes: int
) -> dict:
    """Replicated classification head on the pooled output."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    rep = NamedSharding(sb.mesh, P())
    w = jax.random.normal(rng, (sb.cfg.dim, num_classes)) * sb.cfg.dim**-0.5
    return {
        "cls_w": jax.device_put(w, rep),
        "cls_b": jax.device_put(jnp.zeros((num_classes,)), rep),
    }


def _place_zero1(opt_state, params, mesh, zero1: bool, cell: list):
    """Shared init-side ZeRO-1 placement: device_put the moments with
    zero1_shardings and stash the sharding tree in `cell` for the
    step-side constraint."""
    if not zero1:
        return opt_state
    sh = zero1_shardings(opt_state, params, mesh)
    cell[:] = [sh]
    return jax.device_put(opt_state, sh)


def _make_update_step(
    optimizer,
    loss_fn,
    zero1: bool,
    opt_shardings: list,
    *,
    has_aux: bool = False,
):
    """The one donated train-step body every factory shares:
    value_and_grad over loss_fn(params, *batch), optimizer update,
    ZeRO-1 re-constraint (without it XLA may resolve the elementwise
    moment update to the replicated gradient layout and silently give
    the memory saving back), apply. With has_aux the step returns
    loss_fn's full (loss, aux) tuple."""

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, *batch):
        out, grads = jax.value_and_grad(loss_fn, has_aux=has_aux)(
            state.params, *batch
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        if zero1 and opt_shardings:
            opt_state = jax.lax.with_sharding_constraint(
                opt_state, opt_shardings[0]
            )
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), out

    return train_step


def make_train_step(
    sb: SpmdBert,
    optimizer: optax.GradientTransformation,
    *,
    num_classes: int,
    zero1: bool = False,
) -> tuple[
    Callable[[jax.Array, Any], TrainState],
    Callable[[TrainState, jax.Array, jax.Array], tuple[TrainState, jax.Array]],
]:
    """Returns (init_state, train_step).

    train_step(state, ids [M, B, S], labels [M, B]) -> (state, loss):
    microbatches stream through the pipeline, per-microbatch CLS
    classification losses are averaged, and one optimizer update is
    applied — i.e. M microbatches of gradient accumulation happen
    *inside* the pipelined program, which is exactly what keeps the
    pipeline bubble amortized during training.

    zero1=True additionally shards the optimizer moments over the
    "data" mesh axis (zero1_shardings): identical numerics, 1/dp the
    optimizer HBM per chip.
    """
    forward = sb.make_step()
    # Filled by init_state when zero1 is on; train_step reads it at
    # trace time (init_state always runs first — it builds the state
    # the step consumes).
    opt_shardings: list = []

    def loss_fn(params, ids, labels):
        pooled = forward(params, ids)  # [M, B, D]
        logits = (
            pooled.astype(jnp.float32) @ params["cls_w"] + params["cls_b"]
        )
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        )
        return losses.mean()

    def init_state(rng: jax.Array, extra_params: dict | None = None):
        params = {**sb.init(rng)}
        params.update(
            make_classifier_params(
                jax.random.fold_in(rng, 17), sb, num_classes
            )
        )
        if extra_params:
            params.update(extra_params)
        opt_state = _place_zero1(
            optimizer.init(params), params, sb.mesh, zero1, opt_shardings
        )
        return TrainState(
            params=params,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )

    return init_state, _make_update_step(
        optimizer, loss_fn, zero1, opt_shardings
    )


def _init_lm_params(sb: SpmdBert, rng: jax.Array) -> dict:
    """GptDecoder-keyed LM parameter tree from an SpmdBert init: drop
    the classifier pooler, add the final pre-LN norm the weight-tied
    head expects — shared by the LM and DPO factories."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    base = sb.init(rng)
    rep = NamedSharding(sb.mesh, P())
    params = {
        k: v for k, v in base.items() if k not in ("pooler_w", "pooler_b")
    }
    params["final_ln_scale"] = jax.device_put(jnp.ones((sb.cfg.dim,)), rep)
    if sb.cfg.norm_type == "layer":
        params["final_ln_bias"] = jax.device_put(
            jnp.zeros((sb.cfg.dim,)), rep
        )
    return params


def _lm_logits(sb: SpmdBert, params: dict, ids: jax.Array) -> jax.Array:
    """The pipelined LM forward both objectives share: hidden states
    -> final pre-LN norm -> weight-tied head, fp32 logits [M, B, S, V].
    ONE definition keeps LM-vs-DPO and train-vs-serve parity by
    construction."""
    from defer_tpu.parallel.transformer_stack import _layer_norm, _rms_norm

    cfg = sb.cfg
    h = sb.make_hidden_step()(params, ids).astype(jnp.float32)
    if cfg.norm_type == "rms":
        h = _rms_norm(h, params["final_ln_scale"], cfg.layer_norm_eps)
    else:
        h = _layer_norm(
            h,
            params["final_ln_scale"],
            params["final_ln_bias"],
            cfg.layer_norm_eps,
        )
    return h @ params["token_embedding"].astype(jnp.float32).T


def sequence_logprobs(
    sb: SpmdBert, params: dict, ids: jax.Array, mask: jax.Array
) -> jax.Array:
    """Per-sequence sum of next-token log-probabilities over the
    masked region: ids [M, B, S], mask [M, B, S] (1 where position t's
    TOKEN — predicted from t-1 — counts, e.g. the completion; position
    0 can never count). Returns [M, B] fp32.

    Uses the pipelined hidden-step forward + the weight-tied pre-LN
    head (the same math make_lm_train_step trains), so policy and
    reference scores in DPO come from exactly the serving model."""
    logits = _lm_logits(sb, params, ids)
    logp = jax.nn.log_softmax(logits[..., :-1, :], axis=-1)
    tok_lp = jnp.take_along_axis(
        logp, ids[..., 1:, None], axis=-1
    )[..., 0]  # [M, B, S-1]: logp of token t+1 given prefix
    return (tok_lp * mask[..., 1:].astype(jnp.float32)).sum(axis=-1)


def make_dpo_train_step(
    sb: SpmdBert,
    optimizer: optax.GradientTransformation,
    *,
    beta: float = 0.1,
    zero1: bool = False,
):
    """Direct Preference Optimization through the SPMD pipeline.

    Returns (init_state, train_step) with
    ``train_step(state, ref_params, chosen, rejected, mask_c, mask_r)
    -> (state, (loss, accuracy))``: chosen/rejected are [M, B, S] id
    blocks sharing each pair's prompt, masks mark the completion
    region, and the loss is the Bradley-Terry objective
    ``-log sigmoid(beta * ((pi_c - ref_c) - (pi_r - ref_r)))`` with
    the reference scores computed under stop_gradient from the frozen
    ``ref_params`` (pass the policy's own init for the standard
    recipe). `accuracy` is the fraction of pairs the policy currently
    orders correctly — the metric DPO training should push up.

    Same serve-direct contract as make_lm_train_step (pre-LN causal
    stacks only): the optimized tree drops onto the KV-cache decoder.
    """
    if not sb.cfg.causal or sb.cfg.norm_style != "pre":
        raise ValueError(
            "make_dpo_train_step needs causal=True and "
            "norm_style='pre' (the LM head convention the scores and "
            "the serving decoder share)"
        )
    sb.make_hidden_step()  # build (memoized) outside the jitted loss
    opt_shardings: list = []

    def loss_fn(params, ref_params, chosen, rejected, mask_c, mask_r):
        # Standard DPO batching trick: chosen and rejected stack on
        # the batch axis, so the step pays TWO pipeline traversals
        # (policy + reference), not four.
        b = chosen.shape[1]
        both = jnp.concatenate([chosen, rejected], axis=1)
        mboth = jnp.concatenate([mask_c, mask_r], axis=1)
        pi = sequence_logprobs(sb, params, both, mboth)
        ref = jax.lax.stop_gradient(
            sequence_logprobs(sb, ref_params, both, mboth)
        )
        margin = beta * (
            (pi[:, :b] - ref[:, :b]) - (pi[:, b:] - ref[:, b:])
        )
        loss = -jax.nn.log_sigmoid(margin).mean()
        acc = (margin > 0).mean()
        return loss, acc

    def init_state(rng: jax.Array) -> TrainState:
        params = _init_lm_params(sb, rng)
        opt_state = _place_zero1(
            optimizer.init(params), params, sb.mesh, zero1, opt_shardings
        )
        return TrainState(
            params=params,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )

    return init_state, _make_update_step(
        optimizer, loss_fn, zero1, opt_shardings, has_aux=True
    )


def make_lm_train_step(
    sb: SpmdBert,
    optimizer: optax.GradientTransformation,
    *,
    zero1: bool = False,
) -> tuple[
    Callable[[jax.Array], TrainState],
    Callable[[TrainState, jax.Array], tuple[TrainState, jax.Array]],
]:
    """Next-token language-model training through the SPMD pipeline.

    train_step(state, ids [M, B, S]) -> (state, loss): per-position
    hidden states flow through the pipelined forward
    (SpmdBert.make_hidden_step), a final norm + WEIGHT-TIED head
    (token_embedding.T — the GptDecoder convention) produce [.., S, V]
    logits, and the loss is shifted cross-entropy (position t predicts
    token t+1, mean over the first S-1 positions).

    The trained tree uses GptDecoder's key set (token_embedding /
    pos_embedding / final_ln_* / stack), so after flattening the
    stage-stacked stack ([Stages, L/S, ...] -> [L, ...]) the SAME
    params serve on the KV-cache decoder — train on the pipeline,
    serve with the cache.

    Requires cfg.causal=True: a bidirectional stack under a next-token
    loss would read the answer through attention and "converge"
    instantly without learning anything.
    """
    if not sb.cfg.causal:
        raise ValueError(
            "make_lm_train_step needs cfg.causal=True — a "
            "bidirectional stack leaks each next token to the "
            "position predicting it"
        )
    if sb.cfg.norm_style != "pre":
        raise ValueError(
            "make_lm_train_step needs cfg.norm_style='pre': the final "
            "norm + weight-tied head follow GptDecoder's pre-LN "
            "convention, and a post-norm tree could not serve on the "
            "KV-cache decoder afterwards"
        )
    sb.make_hidden_step()  # build (memoized) outside the jitted loss
    opt_shardings: list = []

    def loss_fn(params, ids):
        logits = _lm_logits(sb, params, ids)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits[..., :-1, :], ids[..., 1:]
        )
        return losses.mean()

    def init_state(rng: jax.Array) -> TrainState:
        params = _init_lm_params(sb, rng)
        opt_state = _place_zero1(
            optimizer.init(params), params, sb.mesh, zero1, opt_shardings
        )
        return TrainState(
            params=params,
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )

    return init_state, _make_update_step(
        optimizer, loss_fn, zero1, opt_shardings
    )
