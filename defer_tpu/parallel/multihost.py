"""Multi-host (multi-process) initialization and mesh layout.

The reference scales out with an IP list and raw sockets (reference
src/test.py:20, src/node_state.py:43-101). The TPU-native equivalent is
`jax.distributed`: every host runs the same SPMD program, the JAX
runtime wires the slice(s), and XLA routes collectives over ICI within
a slice and DCN across slices. This module wraps that bootstrap and
encodes the one layout rule that matters for performance: **axes that
communicate most must stay inside a slice (ICI); only the outermost
data/pipeline axes may span slices (DCN)** — the scaling-book recipe.

For pipelines spanning hosts outside one jax.distributed job (the
reference's heterogeneous-edge deployment model), the host relay in
defer_tpu/runtime/transport.py carries boundary activations instead.
"""

from __future__ import annotations

import os
from typing import Mapping, Sequence

import jax

from defer_tpu.parallel.mesh import make_mesh
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _already_initialized() -> bool:
    """Whether jax.distributed.initialize already ran in this process,
    without touching (and thereby initializing) the XLA backend."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:  # noqa: BLE001 — private-API drift fallback
        return False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Join (or bootstrap) a multi-host JAX job.

    On TPU pods with standard env metadata, bare `initialize()`
    auto-discovers everything; the explicit arguments cover DCN
    clusters without that metadata — the analogue of the reference
    telling every node its peers by hand (reference src/test.py:20),
    but once, at startup, instead of per-edge socket wiring.

    Returns the resulting topology snapshot. Safe to call in
    single-process runs (no coordinator configured -> no-op).
    """
    explicit = coordinator_address is not None
    discovered = any(
        v in os.environ
        for v in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS")
    )
    # jax.distributed.initialize must run before ANY backend-touching
    # call — including jax.process_count() — so "already initialized"
    # is read from the distributed runtime's own state, not the
    # backend.
    if (explicit or discovered) and not _already_initialized():
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif not (explicit or discovered):
        log.info("single-process run; jax.distributed not initialized")
    topo = {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }
    log.info("multihost topology: %s", topo)
    return topo


def dcn_aware_axes(
    axes: Mapping[str, int], *, dcn_axes: Sequence[str] = ("data", "stage")
) -> dict[str, int]:
    """Order mesh axes so only the named outer axes cross hosts.

    jax.sharding.Mesh maps leading axes to the outermost device
    dimension; with `jax.devices()` ordering, devices of one host are
    contiguous, so the leading axes are the ones that span hosts. Axes
    with heavy collectives (model/tensor, sequence, expert) must stay
    inner so their traffic rides ICI; data and pipeline-stage traffic
    is per-step and small (one activation boundary), so those may
    cross DCN.
    """
    hosts = jax.process_count()
    if hosts <= 1:
        return dict(axes)
    outer = {k: v for k, v in axes.items() if k in dcn_axes}
    inner = {k: v for k, v in axes.items() if k not in dcn_axes}
    outer_size = 1
    for v in outer.values():
        outer_size *= v
    if outer_size % hosts != 0 and outer_size != 1:
        log.warning(
            "outer axes %s (size %d) do not tile the %d hosts evenly; "
            "an ICI-heavy axis may end up crossing DCN",
            tuple(outer),
            outer_size,
            hosts,
        )
    return {**outer, **inner}


def make_multihost_mesh(
    axes: Mapping[str, int],
    *,
    dcn_axes: Sequence[str] = ("data", "stage"),
):
    """make_mesh with the DCN-aware axis ordering applied."""
    return make_mesh(dcn_aware_axes(axes, dcn_axes=dcn_axes))


def stage_submeshes(mesh, stage_axis: str = "stage") -> list:
    """Split a mesh carrying a pipeline `stage_axis` into one submesh
    per stage, each over that stage's device slice with the remaining
    axes preserved in order.

    This is how `pp_stages` composes around tensor parallelism
    (runtime/paged.py): build the joint mesh with
    `make_multihost_mesh({"stage": S, model_axis: tp})` — the
    DCN-aware ordering puts `stage` outermost, so each stage's devices
    are host-contiguous and its inner model-axis collectives stay on
    ICI — then each pipeline stage runs its shard_map programs on its
    own submesh while activations hop stage boundaries as replicated
    arrays.
    """
    from jax.sharding import Mesh

    if stage_axis not in mesh.axis_names:
        raise ValueError(
            f"mesh has no {stage_axis!r} axis (axes: {mesh.axis_names}); "
            f"build it with make_multihost_mesh({{{stage_axis!r}: S, "
            "...}})"
        )
    idx = mesh.axis_names.index(stage_axis)
    if idx != 0:
        raise ValueError(
            f"{stage_axis!r} must be the OUTERMOST mesh axis so each "
            "stage's devices are contiguous (dcn_aware_axes puts it "
            f"there); got axis order {mesh.axis_names}"
        )
    rest = tuple(
        n for n in mesh.axis_names if n != stage_axis
    )
    subs = []
    for s in range(mesh.devices.shape[idx]):
        devs = mesh.devices[s]
        subs.append(Mesh(devs, rest))
    return subs
