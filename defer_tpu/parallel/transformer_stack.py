"""Homogeneous transformer-encoder stack with Megatron-style tensor
parallelism, for the SPMD pipeline.

The reference never needed this (its zoo is CNNs shipped whole to CPU
nodes), but BERT-base encoder inference is in its benchmark config list
(BASELINE.json "configs": "BERT-base encoder inference ... transformer
stages"). On TPU the idiomatic layout is: encoder blocks stacked on a
leading layer axis, layer axis sharded over the "stage" mesh axis
(pipeline), weight matrices sharded over a "model" mesh axis (tensor
parallel, partial-sum reductions via psum over ICI), batch sharded over
"data".

Q/K/V projections are separate [D, D] matrices (not a fused [D, 3D]):
under column sharding each tp shard then holds a contiguous head group
of each of q, k, v, so attention is purely local and only the out/ffn
row-parallel matmuls need a psum.

All parameters are plain pytrees of arrays with a leading [L] layer
axis; `stack_specs` gives the matching PartitionSpecs.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from defer_tpu.ops.attention import multi_head_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    num_layers: int = 12
    dim: int = 768
    num_heads: int = 12
    ffn_dim: int = 3072
    vocab_size: int = 30522
    max_len: int = 512
    layer_norm_eps: float = 1e-12


def init_stack(
    rng: jax.Array, cfg: TransformerConfig, dtype: Any = jnp.float32
) -> dict:
    """Parameters for L stacked encoder blocks, leading axis = layer."""
    L, D, F = cfg.num_layers, cfg.dim, cfg.ffn_dim
    ks = jax.random.split(rng, 8)
    s = D**-0.5
    return {
        "wq": jax.random.normal(ks[0], (L, D, D), dtype) * s,
        "wk": jax.random.normal(ks[1], (L, D, D), dtype) * s,
        "wv": jax.random.normal(ks[2], (L, D, D), dtype) * s,
        "bq": jnp.zeros((L, D), dtype),
        "bk": jnp.zeros((L, D), dtype),
        "bv": jnp.zeros((L, D), dtype),
        "wo": jax.random.normal(ks[3], (L, D, D), dtype) * s,
        "bo": jnp.zeros((L, D), dtype),
        "w1": jax.random.normal(ks[4], (L, D, F), dtype) * s,
        "b1": jnp.zeros((L, F), dtype),
        "w2": jax.random.normal(ks[5], (L, F, D), dtype) * (F**-0.5),
        "b2": jnp.zeros((L, D), dtype),
        "ln1_scale": jnp.ones((L, D), dtype),
        "ln1_bias": jnp.zeros((L, D), dtype),
        "ln2_scale": jnp.ones((L, D), dtype),
        "ln2_bias": jnp.zeros((L, D), dtype),
    }


def stack_specs(
    stage_axis: str | None = "stage", tp_axis: str | None = None
) -> dict:
    """PartitionSpecs matching init_stack: layer axis -> stage axis;
    q/k/v/ffn-in column-parallel, out/ffn-out row-parallel over tp."""
    st, tp = stage_axis, tp_axis
    return {
        "wq": P(st, None, tp),
        "wk": P(st, None, tp),
        "wv": P(st, None, tp),
        "bq": P(st, tp),
        "bk": P(st, tp),
        "bv": P(st, tp),
        "w1": P(st, None, tp),
        "b1": P(st, tp),
        "wo": P(st, tp, None),
        "bo": P(st, None),
        "w2": P(st, tp, None),
        "b2": P(st, None),
        "ln1_scale": P(st, None),
        "ln1_bias": P(st, None),
        "ln2_scale": P(st, None),
        "ln2_bias": P(st, None),
    }


def _layer_norm(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(
        x.dtype
    )


def block_apply(
    p: dict,
    x: jax.Array,
    cfg: TransformerConfig,
    *,
    tp_axis: str | None = None,
) -> jax.Array:
    """One post-LN encoder block on (B, S, D); params have no layer axis.

    Under shard_map with tp_axis set, the projections arrive
    column-sharded (local output features = one head group) and wo/w2
    row-sharded: local matmuls produce partial sums reduced with psum
    over the tp axis — the Megatron pattern, collectives on ICI.
    """
    dt = x.dtype
    tp_size = 1 if tp_axis is None else lax.axis_size(tp_axis)
    local_heads = cfg.num_heads // tp_size

    q = x @ p["wq"].astype(dt) + p["bq"].astype(dt)
    k = x @ p["wk"].astype(dt) + p["bk"].astype(dt)
    v = x @ p["wv"].astype(dt) + p["bv"].astype(dt)
    attn = multi_head_attention(
        q, k, v, num_heads=local_heads, use_pallas="auto"
    )
    attn = attn @ p["wo"].astype(dt)
    if tp_axis is not None:
        attn = lax.psum(attn, tp_axis)
    attn = attn + p["bo"].astype(dt)
    x = _layer_norm(
        x + attn, p["ln1_scale"], p["ln1_bias"], cfg.layer_norm_eps
    )

    h = x @ p["w1"].astype(dt) + p["b1"].astype(dt)
    h = jax.nn.gelu(h)
    h = h @ p["w2"].astype(dt)
    if tp_axis is not None:
        h = lax.psum(h, tp_axis)
    h = h + p["b2"].astype(dt)
    return _layer_norm(x + h, p["ln2_scale"], p["ln2_bias"], cfg.layer_norm_eps)


def layers_apply(
    stacked: dict,
    x: jax.Array,
    cfg: TransformerConfig,
    *,
    tp_axis: str | None = None,
) -> jax.Array:
    """Apply a [Llocal, ...]-stacked group of blocks via lax.scan (one
    compiled block body regardless of depth — compiler-friendly)."""

    def body(h, p_one):
        return block_apply(p_one, h, cfg, tp_axis=tp_axis), None

    out, _ = lax.scan(body, x, stacked)
    return out
