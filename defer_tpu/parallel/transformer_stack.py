"""Homogeneous transformer-encoder stack with Megatron-style tensor
parallelism, for the SPMD pipeline.

The reference never needed this (its zoo is CNNs shipped whole to CPU
nodes), but BERT-base encoder inference is in its benchmark config list
(BASELINE.json "configs": "BERT-base encoder inference ... transformer
stages"). On TPU the idiomatic layout is: encoder blocks stacked on a
leading layer axis, layer axis sharded over the "stage" mesh axis
(pipeline), weight matrices sharded over a "model" mesh axis (tensor
parallel, partial-sum reductions via psum over ICI), batch sharded over
"data".

Q/K/V projections are separate [D, D] matrices (not a fused [D, 3D]):
under column sharding each tp shard then holds a contiguous head group
of each of q, k, v, so attention is purely local and only the out/ffn
row-parallel matmuls need a psum.

All parameters are plain pytrees of arrays with a leading [L] layer
axis; `stack_specs` gives the matching PartitionSpecs.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from defer_tpu.ops.attention import multi_head_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    num_layers: int = 12
    dim: int = 768
    num_heads: int = 12
    ffn_dim: int = 3072
    vocab_size: int = 30522
    max_len: int = 512
    layer_norm_eps: float = 1e-12
    # > 0 switches every block's FFN to a top-1-routed mixture of
    # experts (expert-parallel over an "expert" mesh axis).
    num_experts: int = 0
    # "post" = BERT-style residual-then-norm; "pre" = GPT/ViT-style
    # norm-then-sublayer (ln params then normalize the sublayer INPUT,
    # and the residual stream is never normalized in-block).
    norm_style: str = "post"
    # Causal (decoder-style) attention masking: with norm_style="pre"
    # this makes the SPMD stack a trainable GPT — the same params the
    # KV-cache decoder (defer_tpu/models/gpt.py) serves.
    causal: bool = False
    # Sliding-window (Mistral-style) causal attention: each position
    # attends at most `window` predecessors. None = full causal.
    window: int | None = None
    # Rematerialize each block on the backward pass (jax.checkpoint):
    # activation memory drops from O(layers) to O(1) blocks per stage
    # at the cost of one extra forward — the standard TPU trade when
    # HBM, not FLOPs, bounds the trainable model size.
    remat: bool = False
    # MoE dispatch: "dense" computes every local expert for every
    # token and masks (exact, no drops, E_local x the FLOPs); "a2a"
    # routes tokens to their expert's device with lax.all_to_all under
    # a static per-expert capacity (the scaling path for large expert
    # counts — tokens over capacity are dropped, Switch-style).
    moe_dispatch: str = "dense"
    capacity_factor: float = 1.25
    # Experts per token: 1 = Switch (output scaled by the raw top
    # gate), >1 = Mixtral-style (weights renormalized over the
    # selected experts).
    moe_top_k: int = 1
    # -- llama-family knobs (defaults preserve the BERT/GPT behavior;
    #    defer_tpu/models/llama.py sets the full combination) --------
    # Grouped-query attention: K/V project to this many heads (each
    # shared by num_heads/num_kv_heads query heads). None = MHA.
    num_kv_heads: int | None = None
    norm_type: str = "layer"  # "layer" | "rms" (scale-only, no mean)
    ffn_style: str = "gelu"  # "gelu" | "swiglu" (gate*up, biasless F)
    pos_style: str = "learned"  # "learned" table | "rope" (rotary q/k)
    use_bias: bool = True  # llama: no projection biases at all
    rope_theta: float = 10000.0
    # -- LoRA (parallel/lora.py) ------------------------------------
    # rank > 0 adds low-rank adapter factors {t}:a [in, r] / {t}:b
    # [r, out] for each target projection; the forward adds
    # scale * (x @ a) @ b to the frozen base matmul. b starts at zero,
    # so a freshly-initialized adapter is an exact identity.
    lora_rank: int = 0
    lora_targets: tuple = ("wq", "wv")
    lora_alpha: float | None = None  # scale = alpha / rank; None -> 1.0

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def lora_scale(self) -> float:
        if not self.lora_rank:
            return 0.0
        if self.lora_alpha is None:
            return 1.0
        return self.lora_alpha / self.lora_rank

    def __post_init__(self):
        if self.num_heads % self.kv_heads:
            raise ValueError(
                f"num_kv_heads={self.kv_heads} must divide "
                f"num_heads={self.num_heads}"
            )
        if self.ffn_style == "swiglu" and self.num_experts:
            raise ValueError("swiglu MoE blocks are not supported")
        if self.window is not None and (
            self.window < 1 or not self.causal
        ):
            raise ValueError(
                f"window={self.window} needs causal=True and window >= 1"
            )
        if self.capacity_factor <= 0:
            raise ValueError(
                f"capacity_factor={self.capacity_factor} must be > 0 "
                "(non-positive values would silently drop almost every "
                "token to the residual path)"
            )
        if self.num_experts and not (
            1 <= self.moe_top_k <= self.num_experts
        ):
            raise ValueError(
                f"moe_top_k={self.moe_top_k} must be in "
                f"[1, num_experts={self.num_experts}]"
            )
        if self.lora_rank:
            if self.lora_rank < 1:
                raise ValueError(f"lora_rank={self.lora_rank} must be >= 1")
            valid = {"wq", "wk", "wv", "wo", "w1", "w2"}
            if self.ffn_style == "swiglu":
                valid.add("w3")
            if self.num_experts:
                # Expert FFN weights have an extra [E] axis the
                # two-factor adapter doesn't model.
                valid -= {"w1", "w2"}
            bad = set(self.lora_targets) - valid
            if bad:
                raise ValueError(
                    f"lora_targets {sorted(bad)} not adaptable for this "
                    f"config (valid: {sorted(valid)})"
                )
            if not self.lora_targets:
                raise ValueError("lora_rank set but lora_targets is empty")
        # Fail at construction, not as a KeyError deep inside jit
        # tracing (a typo'd knob would otherwise silently select the
        # wrong architecture or crash on a missing param key).
        for field, allowed in (
            ("norm_style", ("post", "pre")),
            ("norm_type", ("layer", "rms")),
            ("ffn_style", ("gelu", "swiglu")),
            ("pos_style", ("learned", "rope")),
            ("moe_dispatch", ("dense", "a2a")),
        ):
            v = getattr(self, field)
            if v not in allowed:
                raise ValueError(
                    f"{field}={v!r}: must be one of {allowed}"
                )


#: Projections whose INPUT axis is tp-sharded (Megatron row-parallel,
#: partial sums closed by the block's psum). Everything else adaptable
#: is column-parallel (output features sharded).
_ROW_PARALLEL = frozenset({"wo", "w2"})


def lora_target_dims(cfg: TransformerConfig) -> dict:
    """(in_dim, out_dim) for every projection an adapter can target."""
    D, F = cfg.dim, cfg.ffn_dim
    dkv = cfg.kv_heads * (D // cfg.num_heads)
    dims = {
        "wq": (D, D),
        "wk": (D, dkv),
        "wv": (D, dkv),
        "wo": (D, D),
        "w1": (D, F),
        "w2": (F, D),
    }
    if cfg.ffn_style == "swiglu":
        dims["w3"] = (D, F)
    return dims


def init_stack(
    rng: jax.Array, cfg: TransformerConfig, dtype: Any = jnp.float32
) -> dict:
    """Parameters for L stacked encoder blocks, leading axis = layer.

    The key set follows the config: GQA narrows wk/wv to the KV head
    width, use_bias=False drops every b*, norm_type="rms" drops the
    norm biases, and ffn_style="swiglu" adds the w3 up-projection."""
    L, D, F = cfg.num_layers, cfg.dim, cfg.ffn_dim
    dkv = cfg.kv_heads * (D // cfg.num_heads)
    ks = jax.random.split(rng, 8)
    s = D**-0.5
    p = {
        "wq": jax.random.normal(ks[0], (L, D, D), dtype) * s,
        "wk": jax.random.normal(ks[1], (L, D, dkv), dtype) * s,
        "wv": jax.random.normal(ks[2], (L, D, dkv), dtype) * s,
        "wo": jax.random.normal(ks[3], (L, D, D), dtype) * s,
        "ln1_scale": jnp.ones((L, D), dtype),
        "ln2_scale": jnp.ones((L, D), dtype),
    }
    if cfg.use_bias:
        p.update(
            {
                "bq": jnp.zeros((L, D), dtype),
                "bk": jnp.zeros((L, dkv), dtype),
                "bv": jnp.zeros((L, dkv), dtype),
                "bo": jnp.zeros((L, D), dtype),
            }
        )
    if cfg.norm_type == "layer":
        p.update(
            {
                "ln1_bias": jnp.zeros((L, D), dtype),
                "ln2_bias": jnp.zeros((L, D), dtype),
            }
        )
    if cfg.ffn_style == "swiglu":
        p["w3"] = jax.random.normal(ks[7], (L, D, F), dtype) * s
    if cfg.num_experts:
        E = cfg.num_experts
        p.update(
            {
                "router": jax.random.normal(ks[6], (L, D, E), dtype) * s,
                "w1": jax.random.normal(ks[4], (L, E, D, F), dtype) * s,
                "b1": jnp.zeros((L, E, F), dtype),
                "w2": jax.random.normal(ks[5], (L, E, F, D), dtype)
                * (F**-0.5),
                "b2": jnp.zeros((L, E, D), dtype),
            }
        )
    else:
        p.update(
            {
                "w1": jax.random.normal(ks[4], (L, D, F), dtype) * s,
                "w2": jax.random.normal(ks[5], (L, F, D), dtype)
                * (F**-0.5),
            }
        )
        if cfg.use_bias:
            p["b1"] = jnp.zeros((L, F), dtype)
            p["b2"] = jnp.zeros((L, D), dtype)
    if cfg.lora_rank:
        r = cfg.lora_rank
        dims = lora_target_dims(cfg)
        for i, t in enumerate(cfg.lora_targets):
            din, dout = dims[t]
            p[f"{t}:a"] = (
                jax.random.normal(
                    jax.random.fold_in(rng, 100 + i), (L, din, r), dtype
                )
                * din**-0.5
            )
            # Zero b => a fresh adapter changes nothing: the fine-tune
            # starts exactly at the pretrained model.
            p[f"{t}:b"] = jnp.zeros((L, r, dout), dtype)
    return p


def stack_specs(
    stage_axis: str | None = "stage",
    tp_axis: str | None = None,
    *,
    ep_axis: str | None = None,
    moe: bool = False,
    cfg: TransformerConfig | None = None,
) -> dict:
    """PartitionSpecs matching init_stack: layer axis -> stage axis;
    q/k/v/ffn-in column-parallel, out/ffn-out row-parallel over tp; with
    moe=True the expert axis of the FFN weights shards over ep_axis.
    Pass `cfg` to tailor the key set to a llama-style stack (dropped
    biases, rms norms, swiglu w3 — all matching init_stack)."""
    st, tp, ep = stage_axis, tp_axis, ep_axis
    use_bias = cfg.use_bias if cfg is not None else True
    layer_norm = cfg.norm_type == "layer" if cfg is not None else True
    swiglu = cfg.ffn_style == "swiglu" if cfg is not None else False
    p = {
        "wq": P(st, None, tp),
        "wk": P(st, None, tp),
        "wv": P(st, None, tp),
        "wo": P(st, tp, None),
        "ln1_scale": P(st, None),
        "ln2_scale": P(st, None),
    }
    if use_bias:
        p.update(
            {
                "bq": P(st, tp),
                "bk": P(st, tp),
                "bv": P(st, tp),
                "bo": P(st, None),
            }
        )
    if layer_norm:
        p.update(
            {
                "ln1_bias": P(st, None),
                "ln2_bias": P(st, None),
            }
        )
    if swiglu:
        p["w3"] = P(st, None, tp)
    if moe:
        p.update(
            {
                "router": P(st, None, None),
                "w1": P(st, ep, None, tp),
                "b1": P(st, ep, tp),
                "w2": P(st, ep, tp, None),
                "b2": P(st, ep, None),
            }
        )
    else:
        p.update(
            {
                "w1": P(st, None, tp),
                "w2": P(st, tp, None),
            }
        )
        if use_bias:
            p["b1"] = P(st, tp)
            p["b2"] = P(st, None)
    if cfg is not None and cfg.lora_rank:
        for t in cfg.lora_targets:
            if t in _ROW_PARALLEL:
                # Input sharded like the base weight's rows; x @ a is a
                # partial sum the block's existing psum closes (the
                # low-rank path rides the same collective by linearity).
                p[f"{t}:a"] = P(st, tp, None)
                p[f"{t}:b"] = P(st, None, None)
            else:
                # Rank axis replicated, output features tp-sharded like
                # the base weight's columns.
                p[f"{t}:a"] = P(st, None, None)
                p[f"{t}:b"] = P(st, None, tp)
    return p


def first_free_divisible_dim(
    spec, dims, dp: int, *, offset: int = 0
) -> int | None:
    """Index (into `dims`) of the first dimension `spec` leaves
    unsharded and the axis size `dp` divides — THE placement rule
    shared by FSDP weight sharding (fsdp_plan, offset=1 to skip the
    stacked layer axis) and ZeRO-1 moment sharding
    (train.zero1_shardings). None if no dim qualifies."""
    spec = list(spec)
    for i, dim in enumerate(dims):
        ax = spec[i + offset] if i + offset < len(spec) else None
        if ax is None and dim % dp == 0 and dim >= dp:
            return i
    return None


def fsdp_plan(
    cfg: TransformerConfig, per_layer_specs: dict, dp: int
) -> dict:
    """FSDP placement: {param key -> per-layer dim index} to shard over
    the data axis (and to all-gather back on use).

    For each stack leaf, pick the first dimension the per-layer spec
    leaves unsharded whose size the data-axis size divides — shapes
    come from an eval_shape of init_stack, so every key the config
    produces (biases, norms, MoE experts, LoRA factors) is planned by
    the same rule. Leaves with no eligible dim (e.g. tp-sharded
    biases) stay as they are: FSDP is a per-leaf memory optimization,
    not an all-or-nothing mode.
    """
    if dp <= 1:
        return {}
    shapes = jax.eval_shape(
        lambda k: init_stack(k, cfg), jax.random.key(0)
    )
    plan: dict = {}
    for key, leaf in shapes.items():
        axis = first_free_divisible_dim(
            per_layer_specs[key], leaf.shape[1:], dp, offset=1
        )
        if axis is not None:
            plan[key] = axis
    return plan


def build_fsdp_plan(cfg: TransformerConfig, per_layer_specs: dict, mesh) -> dict:
    """Shared SpmdBert/SpmdVit fsdp=True setup: validate the mesh has
    a data axis to shard over, then plan per-leaf placement."""
    dp = mesh.shape.get("data", 1)
    if dp <= 1:
        raise ValueError(
            "fsdp=True needs a 'data' mesh axis of size > 1 "
            "(there is nothing to shard the weights over)"
        )
    return fsdp_plan(cfg, per_layer_specs, dp)


def fsdp_specs(per_layer_specs: dict, plan: dict, data_axis: str) -> dict:
    """Apply an fsdp_plan to per-layer PartitionSpecs: entry
    plan[key]+1 (after the layer axis) becomes the data axis."""
    out = dict(per_layer_specs)
    for key, axis in plan.items():
        spec = list(out[key])
        while len(spec) < axis + 2:
            spec.append(None)
        spec[axis + 1] = data_axis
        out[key] = P(*spec)
    return out


def moe_ffn(
    p: dict,
    x: jax.Array,
    *,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    top_k: int = 1,
) -> jax.Array:
    """Top-k mixture-of-experts FFN on (B, S, D) — dense dispatch.

    Expert parallelism by partition-of-experts: each device along
    ep_axis holds E_local experts, computes them for every token, and
    the top-k dispatch mask zeroes the rest before a psum over ep
    combines shards. Dense dispatch keeps shapes static (no capacity /
    token dropping) — the XLA-friendly formulation; a capacity-based
    all_to_all dispatch is the scaling path for large expert counts.

    The router is replicated; routing probabilities are computed over
    the GLOBAL expert count so results are identical for any ep layout.
    """
    dt = x.dtype
    e_local = p["w1"].shape[0]
    ep = 1 if ep_axis is None else lax.axis_size(ep_axis)
    ep_idx = 0 if ep_axis is None else lax.axis_index(ep_axis)

    idx, wts = _route_topk(p["router"], x, top_k)  # (B, S, k)
    _, gate = _dispatch_weights(idx, wts, ep * e_local)  # (B, S, E)
    # This device's expert columns of the global gate matrix.
    dispatch = lax.dynamic_slice_in_dim(
        gate, ep_idx * e_local, e_local, axis=-1
    )  # (B, S, E_local)

    h = (
        jnp.einsum("bsd,edf->ebsf", x, p["w1"].astype(dt))
        + p["b1"].astype(dt)[:, None, None, :]
    )
    h = jax.nn.gelu(h)
    y = jnp.einsum("ebsf,efd->ebsd", h, p["w2"].astype(dt))
    if tp_axis is not None:
        # w1 column- / w2 row-sharded over tp: partial sums, as in the
        # dense FFN.
        y = lax.psum(y, tp_axis)
    y = y + p["b2"].astype(dt)[:, None, None, :]
    out = jnp.einsum(
        "ebsd,bse->bsd", y.astype(jnp.float32), dispatch
    )
    if ep_axis is not None:
        out = lax.psum(out, ep_axis)
    return out.astype(dt)


def _route_topk(router: jax.Array, x: jax.Array, k: int):
    """Shared top-k routing (fp32 softmax over the GLOBAL expert
    count): returns (expert_indices [..., k], weights [..., k]). ONE
    definition for both dispatches — dense/a2a equivalence depends on
    the routing staying identical. k=1 keeps the Switch convention
    (raw top probability as the gate); k>1 renormalizes over the
    selected experts (Mixtral)."""
    logits = x.astype(jnp.float32) @ router.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    w, idx = jax.lax.top_k(probs, k)
    if k > 1:
        w = w / jnp.sum(w, axis=-1, keepdims=True)
    return idx, w


def _dispatch_weights(idx, w, e_global: int):
    """(member [..., E] in {0,1}, gate [..., E]) from top-k routing."""
    sel = jax.nn.one_hot(idx, e_global, dtype=jnp.float32)  # (..., k, E)
    member = sel.sum(axis=-2)
    gate = (sel * w[..., None]).sum(axis=-2)
    return member, gate


def moe_ffn_a2a(
    p: dict,
    x: jax.Array,
    *,
    capacity_factor: float = 1.25,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    top_k: int = 1,
) -> jax.Array:
    """Top-k MoE FFN with all-to-all expert dispatch on (B, S, D).

    The scaling path dense dispatch can't reach: each device along ep
    takes ITS OWN 1/ep slice of the token stream (tokens arrive
    replicated over ep in this stack, so the slice assigns real
    ownership), routes the slice into a static (E, C, D) capacity
    buffer (C = capacity_factor x slice_tokens / E, Switch-style;
    over-capacity tokens fall through on the residual path), moves
    each expert's slots to that expert's device with one
    `lax.all_to_all` over ICI — carrying DISTINCT tokens per sender —
    runs only the local experts, and returns outputs by the inverse
    all_to_all. Per-device expert compute is capacity-bounded
    (cf x N / E_global x E_local tokens) instead of dense's
    N x E_local, and one psum reassembles the replicated output —
    the same closing collective as the dense dispatch.

    Routing matches moe_ffn exactly (one shared _route_topk, per-token
    decisions), so with C large enough to drop nothing the two
    dispatches are numerically equivalent — that equivalence is the
    correctness test.
    """
    import math

    dt = x.dtype
    b, s, d = x.shape
    n = b * s
    e_local = p["w1"].shape[0]
    ep = 1 if ep_axis is None else lax.axis_size(ep_axis)
    e_global = ep * e_local
    if n % ep:
        raise ValueError(
            f"a2a dispatch needs tokens ({n} = {b}x{s}) divisible by "
            f"the expert axis size {ep}"
        )
    n_l = n // ep
    # Each token claims top_k slots, so capacity scales with k.
    cap = max(1, math.ceil(capacity_factor * top_k * n_l / e_global))

    xf = x.reshape(n, d)
    ep_idx = 0 if ep_axis is None else lax.axis_index(ep_axis)
    x_own = lax.dynamic_slice_in_dim(xf, ep_idx * n_l, n_l)  # (n_l, D)
    idx, wts = _route_topk(p["router"], x_own, top_k)  # (n_l, k)
    member, gate = _dispatch_weights(idx, wts, e_global)  # (n_l, E)

    # Arrival-order position of each token within each selected
    # expert's queue; positions >= cap are dropped (Switch-style).
    member_i = member.astype(jnp.int32)
    pos_in_e = jnp.cumsum(member_i, axis=0) - 1  # (n_l, E)
    keep = (pos_in_e < cap) & (member_i > 0)
    dispatch = (
        jax.nn.one_hot(pos_in_e, cap, dtype=jnp.float32)
        * keep[..., None]
    )  # (n_l, E, C)
    combine = dispatch * gate[..., None].astype(jnp.float32)

    xin = jnp.einsum("nd,nec->ecd", x_own.astype(jnp.float32), dispatch)
    if ep_axis is not None:
        # (E, C, D) -> (E_local, ep*C, D): expert-group rows k go to
        # device k (split over the expert axis); the received sender
        # chunks concatenate on the slot axis in sender order, so
        # slot block j belongs to device j for the inverse route.
        xin = lax.all_to_all(
            xin, ep_axis, split_axis=0, concat_axis=1, tiled=True
        )

    h = jnp.einsum("ecd,edf->ecf", xin.astype(dt), p["w1"].astype(dt))
    h = h + p["b1"].astype(dt)[:, None, :]
    h = jax.nn.gelu(h)
    y = jnp.einsum("ecf,efd->ecd", h, p["w2"].astype(dt))
    if tp_axis is not None:
        y = lax.psum(y, tp_axis)
    y = y + p["b2"].astype(dt)[:, None, :]

    if ep_axis is not None:
        # Inverse route: slot chunks return to their sender, expert
        # chunks stack back into global expert order.
        y = lax.all_to_all(
            y, ep_axis, split_axis=1, concat_axis=0, tiled=True
        )

    out_own = jnp.einsum(
        "ecd,nec->nd", y.astype(jnp.float32), combine
    )  # (n_l, D) — expert outputs for THIS device's token slice
    if ep_axis is None:
        return out_own.astype(dt).reshape(b, s, d)
    # Reassemble the replicated stream: each device contributes its
    # slice, one psum (dense's closing collective) sums the disjoint
    # contributions and returns the shard_map type to replicated.
    out = jnp.zeros((n, d), jnp.float32)
    out = lax.dynamic_update_slice(out, out_own, (ep_idx * n_l, 0))
    out = lax.psum(out, ep_axis)
    return out.astype(dt).reshape(b, s, d)


def embed_lookup(
    table: Any, ids: jax.Array, tp_axis: str | None = None
) -> jax.Array:
    """Token-embedding gather shared by every decoder family (gpt,
    llama, t5).

    Plain [V, D] tables gather directly; int8 weight-only tables
    ({"q", "s"}, models/quant.py) gather the int8 rows and widen just
    the gathered [B, T, D] slice. With tp_axis set (inside shard_map)
    the table is vocab-ROW sharded (Megatron): this shard owns rows
    [v0, v0 + V_local), out-of-range ids contribute zeros, and one
    psum assembles full embeddings."""
    quant = isinstance(table, dict) and "q" in table
    rows = table["q"] if quant else table
    if tp_axis is None:
        emb = jnp.take(rows, ids, axis=0)
        if quant:
            emb = emb.astype(jnp.float32) * table["s"]
        return emb
    v_local = rows.shape[0]
    v0 = lax.axis_index(tp_axis) * v_local
    local_ids = ids - v0
    in_range = (local_ids >= 0) & (local_ids < v_local)
    emb = jnp.take(rows, jnp.clip(local_ids, 0, v_local - 1), axis=0)
    if quant:
        emb = emb.astype(jnp.float32) * table["s"]
    emb = jnp.where(in_range[..., None], emb, 0.0)
    return lax.psum(emb, tp_axis)


def _layer_norm(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(
        x.dtype
    )


def _rms_norm(x, scale, eps):
    """Scale-only RMS normalization (llama), fp32 statistics."""
    xf = x.astype(jnp.float32)
    out = xf * lax.rsqrt(jnp.mean(jnp.square(xf), -1, keepdims=True) + eps)
    return (out * scale.astype(jnp.float32)).astype(x.dtype)


def norm_apply(cfg: TransformerConfig, x, p: dict, which: str):
    """The config's normalization ("ln1"/"ln2" param group)."""
    if cfg.norm_type == "rms":
        return _rms_norm(x, p[f"{which}_scale"], cfg.layer_norm_eps)
    return _layer_norm(
        x, p[f"{which}_scale"], p[f"{which}_bias"], cfg.layer_norm_eps
    )


def apply_rope(
    x_flat: jax.Array,
    head_dim: int,
    positions: jax.Array,
    theta: float,
) -> jax.Array:
    """Rotary position embedding on a flat (B, T, H*Dh) projection.

    Rotation is per-head and head-independent, so reshaping to
    (B, T, H, Dh) handles any head count — the SAME helper serves full
    q, GQA-narrow k, and tensor-parallel local shards. Pairing is the
    rotate-half convention (first half with second half), matching HF
    transformers' llama so checkpoints transplant bit-compatibly.
    `positions` are the ABSOLUTE sequence positions of the T tokens:
    shape (T,) shared across the batch (decode passes cache_pos +
    arange(T); sequence-parallel shards pass their global offsets) or
    (B, T) per batch element (continuous batching, where every slot
    sits at its own depth)."""
    b, t, d = x_flat.shape
    x = x_flat.reshape(b, t, d // head_dim, head_dim)
    half = head_dim // 2
    freqs = theta ** (
        -jnp.arange(0, half, dtype=jnp.float32) * 2.0 / head_dim
    )
    ang = positions.astype(jnp.float32)[..., None] * freqs  # (..., T, half)
    if ang.ndim == 2:  # shared positions -> add the batch axis
        ang = ang[None]
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(
        jnp.float32
    )
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x_flat.dtype)
    return out.reshape(b, t, d)


def repeat_kv(x_flat: jax.Array, head_dim: int, groups: int) -> jax.Array:
    """Expand a flat (B, T, H_kv*Dh) K/V projection to (B, T, H*Dh) by
    repeating each KV head for its query-head group (GQA)."""
    if groups == 1:
        return x_flat
    b, t, d = x_flat.shape
    x = x_flat.reshape(b, t, d // head_dim, head_dim)
    x = jnp.repeat(x, groups, axis=2)
    return x.reshape(b, t, d * groups)


def block_apply(
    p: dict,
    x: jax.Array,
    cfg: TransformerConfig,
    *,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
    sp_strategy: str = "ring",
    ep_axis: str | None = None,
) -> jax.Array:
    """One encoder block on (B, S, D) (post- or pre-LN per
    cfg.norm_style); params have no layer axis.

    Under shard_map with tp_axis set, the projections arrive
    column-sharded (local output features = one head group) and wo/w2
    row-sharded: local matmuls produce partial sums reduced with psum
    over the tp axis — the Megatron pattern, collectives on ICI.

    With sp_axis set, S is the LOCAL sequence shard and attention runs
    ring / Ulysses over that mesh axis (defer_tpu/parallel/sequence.py);
    everything else in the block is per-token and needs no collective.
    """
    dt = x.dtype
    tp_size = 1 if tp_axis is None else lax.axis_size(tp_axis)
    local_heads = cfg.num_heads // tp_size
    dh = cfg.dim // cfg.num_heads
    groups = cfg.num_heads // cfg.kv_heads
    pre = cfg.norm_style == "pre"

    def bias(h, name):
        return h + p[name].astype(dt) if name in p else h

    lora_scale = cfg.lora_scale

    def proj(h, name):
        """Base matmul plus the low-rank adapter path when present.
        Under tp the adapter factors are sharded to match the base
        weight (stack_specs), so no extra collective is needed."""
        y = h @ p[name].astype(dt)
        a = p.get(f"{name}:a")
        if a is not None:
            y = y + ((h @ a.astype(dt)) @ p[f"{name}:b"].astype(dt)) * lora_scale
        return y

    a_in = norm_apply(cfg, x, p, "ln1") if pre else x
    q = bias(proj(a_in, "wq"), "bq")
    k = bias(proj(a_in, "wk"), "bk")
    v = bias(proj(a_in, "wv"), "bv")
    if cfg.pos_style == "rope":
        s_local = q.shape[1]
        offset = (
            0 if sp_axis is None else lax.axis_index(sp_axis) * s_local
        )
        positions = offset + jnp.arange(s_local)
        q = apply_rope(q, dh, positions, cfg.rope_theta)
        k = apply_rope(k, dh, positions, cfg.rope_theta)
    # GQA: expand KV head groups AFTER rope so each query head in a
    # group attends its shared (rotated) KV head.
    k = repeat_kv(k, dh, groups)
    v = repeat_kv(v, dh, groups)
    attn = multi_head_attention(
        q,
        k,
        v,
        num_heads=local_heads,
        causal=cfg.causal,
        window=cfg.window,
        use_pallas="auto",
        sp_axis=sp_axis,
        sp_strategy=sp_strategy,
    )
    attn = proj(attn, "wo")
    if tp_axis is not None:
        attn = lax.psum(attn, tp_axis)
    attn = bias(attn, "bo")
    if pre:
        x = x + attn
        f_in = norm_apply(cfg, x, p, "ln2")
    else:
        x = norm_apply(cfg, x + attn, p, "ln1")
        f_in = x

    if "router" in p:
        if cfg.moe_dispatch == "a2a":
            h = moe_ffn_a2a(
                p,
                f_in,
                capacity_factor=cfg.capacity_factor,
                tp_axis=tp_axis,
                ep_axis=ep_axis,
                top_k=cfg.moe_top_k,
            )
        else:
            h = moe_ffn(
                p,
                f_in,
                tp_axis=tp_axis,
                ep_axis=ep_axis,
                top_k=cfg.moe_top_k,
            )
    elif cfg.ffn_style == "swiglu":
        # llama FFN: silu(gate) * up -> down (w1=gate, w3=up, w2=down).
        gate = jax.nn.silu(proj(f_in, "w1"))
        h = proj(gate * proj(f_in, "w3"), "w2")
        if tp_axis is not None:
            h = lax.psum(h, tp_axis)
    else:
        h = bias(proj(f_in, "w1"), "b1")
        h = jax.nn.gelu(h)
        h = proj(h, "w2")
        if tp_axis is not None:
            h = lax.psum(h, tp_axis)
        h = bias(h, "b2")
    if pre:
        return x + h
    return norm_apply(cfg, x + h, p, "ln2")


def layers_apply(
    stacked: dict,
    x: jax.Array,
    cfg: TransformerConfig,
    *,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
    sp_strategy: str = "ring",
    ep_axis: str | None = None,
    fsdp_axis: str | None = None,
    fsdp_gather: dict | None = None,
) -> jax.Array:
    """Apply a [Llocal, ...]-stacked group of blocks via lax.scan (one
    compiled block body regardless of depth — compiler-friendly).
    cfg.remat wraps the block in jax.checkpoint: the scan then saves
    only each block's INPUT for the backward pass and recomputes the
    block internals, so activation memory per stage stays O(1) blocks
    (collectives inside the block — psum/all_to_all/ppermute — are
    replayed too, which XLA handles).

    With fsdp_axis set, each leaf named in fsdp_gather arrives sharded
    over that mesh axis on dim fsdp_gather[key] and is all-gathered
    JUST IN TIME inside the block body — classic FSDP: at-rest weight
    memory is 1/dp per chip, only the current block's weights are ever
    whole, and the gather's transpose is automatically the
    reduce-scatter the sharded gradients need. The gather sits inside
    the remat boundary, so cfg.remat re-gathers on the backward pass
    instead of keeping full weights alive."""

    def block(p_one, h):
        if fsdp_axis is not None and fsdp_gather:
            p_one = {
                k: (
                    lax.all_gather(
                        v, fsdp_axis, axis=fsdp_gather[k], tiled=True
                    )
                    if k in fsdp_gather
                    else v
                )
                for k, v in p_one.items()
            }
        return block_apply(
            p_one,
            h,
            cfg,
            tp_axis=tp_axis,
            sp_axis=sp_axis,
            sp_strategy=sp_strategy,
            ep_axis=ep_axis,
        )

    if cfg.remat:
        # prevent_cse=False: scan's staging already rules out the CSE
        # that flag guards against, and the default's optimization
        # barriers would block XLA fusion inside every block.
        block = jax.checkpoint(block, prevent_cse=False)

    def body(h, p_one):
        return block(p_one, h), None

    out, _ = lax.scan(body, x, stacked)
    return out
