"""SPMD circular pipeline: shard_map + lax.ppermute over a "stage" mesh
axis.

This is the fully-compiled TPU analogue of the reference's streaming
pipeline (SURVEY.md §3.3): where the reference overlaps stages with
per-node recv/compute/send threads over TCP (reference
src/node.py:97-133), here ONE XLA program runs on every core; each step
every core applies its stage to its current activation and
`lax.ppermute` rotates activations one hop along the ring — the
transfer is an ICI collective the compiler schedules to overlap with
compute. M microbatches drain in M + S - 1 steps (the classic
warm-up/drain bubble).

Requires homogeneous stages (same activation shape/dtype per hop and
identically-structured per-stage params stacked on a leading axis) —
the transformer-encoder case. Heterogeneous CNN chains use
defer_tpu.parallel.pipeline.Pipeline instead.

Composes with a "data" mesh axis (microbatch batch-dim sharding) and a
"model" mesh axis (Megatron tensor parallelism inside the stage fn, see
defer_tpu/parallel/transformer_stack.py).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P


def make_spmd_pipeline(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    param_specs: Any,
    *,
    stage_axis: str = "stage",
    data_axis: str | None = None,
    seq_axis: str | None = None,
) -> Callable[[Any, jax.Array], jax.Array]:
    """Build the pipelined step function.

    Args:
      mesh: mesh containing `stage_axis` (and optionally data/model/seq
        axes).
      stage_fn: (stage-local params, activation [B, ...]) -> activation of
        the SAME shape/dtype; runs inside shard_map, so it may use
        collectives over other mesh axes (e.g. psum over "model", ring
        attention over "seq").
      param_specs: pytree of PartitionSpecs for the stacked stage params
        (leading axis must be sharded over `stage_axis`).
      data_axis: mesh axis to shard the microbatch batch dim over.
      seq_axis: mesh axis to shard the activation's axis 1 after batch
        (the sequence dim of [M, B, S, ...]) over — sequence
        parallelism; stage_fn then sees the local shard.

    Returns:
      run(stacked_params, xs): xs [M, B, ...] -> ys [M, B, ...], jittable.
      The global output buffer is exactly [M, B, ...]: non-final stages'
      per-step emissions are masked and psum-reduced away inside the
      shard_map rather than materialized as [S, M+S-1, B, ...].
    """
    num_stages = mesh.shape[stage_axis]
    shift = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def pipelined(params_local, xs_local):
        # shard_map keeps sharded axes as size-1 local dims; strip the
        # stage axis so stage_fn sees clean per-stage params.
        params_local = jax.tree_util.tree_map(
            lambda a, s: a[0] if tuple(s) and tuple(s)[0] == stage_axis else a,
            params_local,
            param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        num_mb = xs_local.shape[0]
        stage_id = lax.axis_index(stage_axis)
        steps = num_mb + num_stages - 1
        # The carry becomes device-varying after the first ppermute;
        # mark the initial value as varying so scan's types line up.
        buf = lax.pcast(
            jnp.zeros_like(xs_local[0]), (stage_axis,), to="varying"
        )

        def step(carry, t):
            # Stage 0 injects microbatch t; everyone else consumes the
            # activation its left neighbour pushed last step.
            x_t = xs_local[jnp.minimum(t, num_mb - 1)]
            inp = jnp.where(stage_id == 0, x_t, carry)
            out = stage_fn(params_local, inp)
            return lax.ppermute(out, stage_axis, shift), out

        _, emits = lax.scan(step, buf, jnp.arange(steps))
        # Only the final stage's steady-state tail is meaningful: mask
        # the other stages' emissions and reduce over the stage axis so
        # the global output buffer is [M, B, ...] — not the S x
        # (M+S-1) materialization of every stage's per-step outputs.
        tail = lax.dynamic_slice_in_dim(
            emits, num_stages - 1, num_mb, axis=0
        )
        is_last = stage_id == num_stages - 1
        tail = jnp.where(is_last, tail, jnp.zeros_like(tail))
        return lax.psum(tail, stage_axis)

    act_axes = (data_axis,) if seq_axis is None else (data_axis, seq_axis)
    in_specs = (param_specs, P(None, *act_axes))
    out_specs = P(None, *act_axes)
    return jax.shard_map(
        pipelined, mesh=mesh, in_specs=in_specs, out_specs=out_specs
    )


def stack_for_stages(params: Any, num_stages: int) -> Any:
    """Reshape leading [L, ...] leaves to [S, L // S, ...] so the layer
    axis can be sharded over the stage axis."""

    def reshape(leaf):
        L = leaf.shape[0]
        if L % num_stages:
            raise ValueError(
                f"layer count {L} not divisible by {num_stages} stages"
            )
        return leaf.reshape(num_stages, L // num_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, params)


def staged_specs(specs: Any, stage_axis: str = "stage") -> Any:
    """Prepend the stage axis to per-layer specs (for stack_for_stages
    output): P(a, b, ...) -> P(stage, a, b, ...)."""
    return jax.tree_util.tree_map(
        lambda s: P(stage_axis, *tuple(s)),
        specs,
        is_leaf=lambda s: isinstance(s, P),
    )
