"""The five static rules. Each is a pure function over the parsed
corpus; adding a rule = one function + one RULES entry (see
ARCHITECTURE.md "Static analysis & sanitizers").

Design bias: these guard a serving codebase, so rules prefer recall on
the hot paths and keep cold paths quiet — `np.asarray` is only a
finding where it runs per decode tick, a jit-of-closure is only a
finding where it re-traces per call. Anything intentional gets an
inline ``# analysis: ignore[rule] reason`` (ignore.py) instead of a
rule carve-out, so the justification lives next to the hazard.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Callable, Iterator

from defer_tpu.analysis.callgraph import DEFAULT_ROOTS, CallGraph, FuncInfo


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule}: {self.message}"
        )


@dataclasses.dataclass
class Module:
    path: str
    source: str
    tree: ast.AST


@dataclasses.dataclass
class Context:
    modules: list[Module]
    graph: CallGraph
    roots: tuple[str, ...] = DEFAULT_ROOTS

    def hot(self) -> set[int]:
        if not hasattr(self, "_hot"):
            self._hot = self.graph.hot_set(self.roots)
        return self._hot


def _dotted(node: ast.AST) -> str | None:
    """'jax.random.normal' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk_shallow(node: ast.AST) -> Iterator[ast.AST]:
    """Document-order walk of one function's own body: nested defs and
    lambdas are separate analysis units (the call graph decides if
    *they* are hot), so their bodies are not yielded here."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, _FUNC_NODES):
            continue
        yield child
        yield from _walk_shallow(child)


def _root_name(node: ast.AST) -> str | None:
    """Base Name of a Name/Subscript chain: `host[i]` -> 'host'."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


# -- host-sync-in-hot-loop --------------------------------------------

_NP_MODULES = {"np", "numpy", "onp"}
_SYNC_ATTRS = {"item", "block_until_ready"}


def _host_transfer_call(call: ast.Call) -> str | None:
    """Name the host transfer if this call is one, else None."""
    f = call.func
    dotted = _dotted(f)
    if dotted in ("jax.device_get", "device_get"):
        return dotted
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id in _NP_MODULES
        and f.attr in ("asarray", "array")
    ):
        return f"{f.value.id}.{f.attr}"
    return None


def _host_exprs(value: ast.AST) -> Iterator[ast.AST]:
    """Unwrap conditional assigns: `np.asarray(x) if c else None`."""
    if isinstance(value, ast.IfExp):
        yield from _host_exprs(value.body)
        yield from _host_exprs(value.orelse)
    else:
        yield value


def rule_host_sync(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    hot = ctx.hot()
    for fi in ctx.graph.functions:
        if id(fi.node) not in hot:
            continue
        # Names assigned from an (already flagged) host transfer are
        # host data: `int(host_nxt[i])` after `host_nxt = np.asarray(..)`
        # costs nothing extra and is not re-flagged.
        host_names: set[str] = set()
        for node in _walk_shallow(fi.node):
            if isinstance(node, ast.Assign):
                for v in _host_exprs(node.value):
                    if isinstance(v, ast.Call) and _host_transfer_call(v):
                        for tgt in node.targets:
                            name = _root_name(tgt)
                            if name:
                                host_names.add(name)
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            what = _host_transfer_call(node)
            if what is None and isinstance(f, ast.Attribute):
                if f.attr in _SYNC_ATTRS:
                    what = f".{f.attr}()"
            if what is None and isinstance(f, ast.Name):
                # int(arr[i]) / float(arr[i]): per-element device
                # indexing, one sync each. Plain int(x) is too often a
                # python scalar to judge statically, so only the
                # subscript form is flagged.
                if f.id in ("int", "float") and len(node.args) == 1:
                    arg = node.args[0]
                    name = _root_name(arg)
                    if (
                        isinstance(arg, ast.Subscript)
                        and name is not None
                        and name not in host_names
                    ):
                        what = f"{f.id}() on a subscripted device value"
            if what is not None:
                out.append(
                    Finding(
                        "host-sync-in-hot-loop",
                        fi.path,
                        node.lineno,
                        node.col_offset,
                        f"{what} in `{fi.qualname.split(':', 1)[1]}`, "
                        f"which is reachable from serving roots "
                        f"{ctx.roots} — a device sync per tick/step; "
                        "batch it behind the Retirer or justify with "
                        "an ignore",
                    )
                )
    return out


# -- fresh-closure-jit ------------------------------------------------


def _is_jit(call: ast.Call) -> bool:
    return _dotted(call.func) in ("jax.jit", "jit")


class _JitVisitor(ast.NodeVisitor):
    def __init__(self, mod: Module, hot: set[int], out: list[Finding]):
        self.mod = mod
        self.hot = hot
        self.out = out
        self.loop_depth = 0
        # Enclosing functions, innermost last; each entry carries the
        # names of defs nested inside it (fresh per call) and the ids
        # of jit calls whose result the function RETURNS — the builder
        # pattern, where a caller (cached_step/jit_cached) memoizes.
        self.func_stack: list[tuple[ast.AST, set[str], set[int]]] = []

    def _local_def_names(self, node: ast.AST) -> set[str]:
        names = set()
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if sub is not node:
                    names.add(sub.name)
        return names

    def _returned_calls(self, node: ast.AST) -> set[int]:
        out: set[int] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Return) and sub.value is not None:
                out.update(
                    id(c)
                    for c in ast.walk(sub.value)
                    if isinstance(c, ast.Call)
                )
        return out

    def visit_For(self, node: ast.For) -> None:
        self._loop(node)

    def visit_While(self, node: ast.While) -> None:
        self._loop(node)

    def _loop(self, node: ast.AST) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._func(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._func(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._func(node)

    def _func(self, node: ast.AST) -> None:
        # A loop wrapping the *definition* does not wrap the body.
        saved = self.loop_depth
        self.loop_depth = 0
        self.func_stack.append(
            (node, self._local_def_names(node), self._returned_calls(node))
        )
        self.generic_visit(node)
        self.func_stack.pop()
        self.loop_depth = saved

    def _fresh_closure(self, arg: ast.AST) -> bool:
        if isinstance(arg, ast.Lambda):
            return True
        if isinstance(arg, ast.Name) and self.func_stack:
            return arg.id in self.func_stack[-1][1]
        return False

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit(node) and node.args:
            arg = node.args[0]
            in_func = bool(self.func_stack)
            in_hot = in_func and id(self.func_stack[-1][0]) in self.hot
            # `return jax.jit(fn)` hands the callable to the caller for
            # memoization (the cached_step builder idiom) — only flag
            # that when it sits inside a loop.
            returned = in_func and id(node) in self.func_stack[-1][2]
            fresh = self._fresh_closure(arg)
            if fresh and (
                self.loop_depth > 0 or (in_hot and not returned)
            ):
                where = (
                    "inside a loop" if self.loop_depth else "on a hot path"
                )
                self.out.append(
                    Finding(
                        "fresh-closure-jit",
                        self.mod.path,
                        node.lineno,
                        node.col_offset,
                        "jax.jit of a closure created per iteration/call "
                        f"{where}: jit's cache is keyed on the function "
                        "OBJECT, so this re-traces every time — memoize "
                        "via utils/memo.cached_step or memo.jit_cached",
                    )
                )
        # jax.jit(f)(x): the jitted callable is dropped immediately, so
        # its cache dies with it — every call re-traces. This form is a
        # finding regardless of what f is.
        if (
            isinstance(node.func, ast.Call)
            and _is_jit(node.func)
        ):
            self.out.append(
                Finding(
                    "fresh-closure-jit",
                    self.mod.path,
                    node.lineno,
                    node.col_offset,
                    "jax.jit(f)(...) discards the jitted callable after "
                    "one call, so its compile cache can never hit — bind "
                    "it once (module level or memo.jit_cached)",
                )
            )
        self.generic_visit(node)


def rule_fresh_closure_jit(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    hot = ctx.hot()
    for mod in ctx.modules:
        _JitVisitor(mod, hot, out).visit(mod.tree)
    return out


# -- prng-key-reuse ---------------------------------------------------

_KEY_PRODUCERS = {"key", "PRNGKey", "split", "fold_in", "clone"}
_KEY_NEUTRAL = _KEY_PRODUCERS | {"wrap_key_data", "key_data", "key_impl"}


def _random_attr(call: ast.Call) -> str | None:
    """'normal' for jax.random.normal(...) / random.normal(...)."""
    dotted = _dotted(call.func)
    if dotted is None:
        return None
    parts = dotted.split(".")
    if len(parts) >= 2 and parts[-2] == "random":
        return parts[-1]
    return None


def _key_id(node: ast.AST) -> object | None:
    """Track plain names and constant-indexed subscripts: `ks[3]` and
    `ks[4]` are distinct keys; `ks[i]` is untrackable (None)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Subscript) and isinstance(
        node.value, ast.Name
    ):
        idx = node.slice
        if isinstance(idx, ast.Constant):
            return (node.value.id, idx.value)
    return None


def _expr_calls(stmt: ast.AST) -> Iterator[ast.Call]:
    """Call nodes of one statement's expressions, document order,
    not descending into nested function/lambda bodies or into the
    bodies of compound statements (handled by _prng_block)."""
    skip = (*_FUNC_NODES, ast.If, ast.For, ast.While, ast.With, ast.Try)

    def rec(node: ast.AST) -> Iterator[ast.Call]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, skip) or isinstance(child, ast.stmt):
                continue
            if isinstance(child, ast.Call):
                yield child
            yield from rec(child)

    if isinstance(stmt, ast.Call):
        yield stmt
    yield from rec(stmt)


def _prng_stmt(
    stmt: ast.AST,
    draws: dict[object, int],
    out: list[Finding],
    path: str,
) -> None:
    for call in _expr_calls(stmt):
        attr = _random_attr(call)
        if attr is None or attr in _KEY_NEUTRAL:
            continue
        key_arg = call.args[0] if call.args else None
        for kw in call.keywords:
            if kw.arg == "key":
                key_arg = kw.value
        kid = _key_id(key_arg) if key_arg is not None else None
        if kid is None:
            continue
        draws[kid] = draws.get(kid, 0) + 1
        if draws[kid] == 2:
            name = kid if isinstance(kid, str) else (
                f"{kid[0]}[{kid[1]!r}]"
            )
            out.append(
                Finding(
                    "prng-key-reuse",
                    path,
                    call.lineno,
                    call.col_offset,
                    f"PRNG key `{name}` feeds a second "
                    f"jax.random.{attr} draw with no intervening "
                    "split — the two draws are perfectly "
                    "correlated; jax.random.split first",
                )
            )
    if isinstance(stmt, ast.Assign):
        # Any rebind of a name makes it a fresh key (or not a key at
        # all) — reset its draw count.
        for tgt in stmt.targets:
            elts = (
                tgt.elts
                if isinstance(tgt, (ast.Tuple, ast.List))
                else [tgt]
            )
            for e in elts:
                kid = _key_id(e)
                if kid is not None:
                    draws[kid] = 0


def _terminates(stmts: list[ast.stmt]) -> bool:
    """A branch ending in return/raise/break/continue never reaches
    the statements after the `if` — its draw state must not merge
    into the fall-through path."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue)
    )


def _prng_block(
    stmts: list[ast.stmt],
    draws: dict[object, int],
    out: list[Finding],
    path: str,
) -> None:
    """Statement interpreter with branch awareness: exclusive `if`
    arms each start from the pre-branch state and merge by max, so one
    draw per arm is not 'two draws'. Loop bodies run once (a single
    textual draw repeated by iteration is a known miss)."""
    for stmt in stmts:
        if isinstance(stmt, (*_FUNC_NODES, ast.ClassDef)):
            continue  # separate analysis units
        if isinstance(stmt, ast.If):
            _prng_stmt(stmt.test, draws, out, path)
            d_then, d_else = dict(draws), dict(draws)
            _prng_block(stmt.body, d_then, out, path)
            _prng_block(stmt.orelse, d_else, out, path)
            live = [
                d for d, body in ((d_then, stmt.body), (d_else, stmt.orelse))
                if not _terminates(body)
            ] or [d_then, d_else]
            for k in set().union(*live):
                draws[k] = max(d.get(k, 0) for d in live)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            _prng_stmt(stmt.iter, draws, out, path)
            _prng_block(stmt.body, draws, out, path)
            _prng_block(stmt.orelse, draws, out, path)
        elif isinstance(stmt, ast.While):
            _prng_stmt(stmt.test, draws, out, path)
            _prng_block(stmt.body, draws, out, path)
            _prng_block(stmt.orelse, draws, out, path)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                _prng_stmt(item.context_expr, draws, out, path)
            _prng_block(stmt.body, draws, out, path)
        elif isinstance(stmt, ast.Try):
            _prng_block(stmt.body, draws, out, path)
            for h in stmt.handlers:
                _prng_block(h.body, draws, out, path)
            _prng_block(stmt.orelse, draws, out, path)
            _prng_block(stmt.finalbody, draws, out, path)
        else:
            _prng_stmt(stmt, draws, out, path)


def rule_prng_key_reuse(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    for fi in ctx.graph.functions:
        body = getattr(fi.node, "body", [])
        if isinstance(body, list):
            _prng_block(body, {}, out, fi.path)
    return out


# -- lock-discipline --------------------------------------------------

_BLOCKING = {
    "join",
    "accept",
    "recv",
    "recv_into",
    "recvfrom",
    "sendall",
    "connect",
    "create_connection",
    "predict",
    "sleep",
}


def _mentions_lock(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return "lock" in node.id.lower()
    if isinstance(node, ast.Attribute):
        return "lock" in node.attr.lower()
    if isinstance(node, ast.Call):  # e.g. `with lock_for(x):`
        return _mentions_lock(node.func)
    return False


def _first_blocking_call(fn_node: ast.AST) -> tuple[str, int] | None:
    """(name, line) of the first blocking call in a function's own
    body (nested defs excluded), else None."""
    for sub in _walk_shallow(fn_node):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        name = (
            f.attr
            if isinstance(f, ast.Attribute)
            else f.id
            if isinstance(f, ast.Name)
            else None
        )
        if name in _BLOCKING:
            return name, sub.lineno
    return None


def _lock_body_calls(
    node: ast.With | ast.AsyncWith,
) -> Iterator[ast.Call]:
    for stmt in node.body:
        for sub in [stmt, *_walk_shallow(stmt)]:
            if isinstance(sub, ast.Call):
                yield sub


def rule_lock_discipline(ctx: Context) -> list[Finding]:
    out: list[Finding] = []

    def check_with(node: ast.AST, fi: FuncInfo | None, path: str) -> None:
        if not any(
            _mentions_lock(item.context_expr) for item in node.items
        ):
            return
        for sub in _lock_body_calls(node):
            f = sub.func
            bare = isinstance(f, ast.Name)
            name = (
                f.attr
                if isinstance(f, ast.Attribute)
                else f.id
                if bare
                else None
            )
            if name is None:
                continue
            if name in _BLOCKING:
                out.append(
                    Finding(
                        "lock-discipline",
                        path,
                        sub.lineno,
                        sub.col_offset,
                        f"blocking call .{name}() while holding "
                        "a lock — every other thread touching "
                        "this lock stalls behind the I/O; move "
                        "the wait outside the critical section",
                    )
                )
                continue
            # One level through the callgraph: a helper whose own body
            # blocks is the same stall, just hidden behind a call. Any
            # name-resolved candidate blocking is a finding (open-world
            # recall bias, same as the hot set).
            for cand in ctx.graph.resolve_call(fi, name, bare):
                hit = _first_blocking_call(cand.node)
                if hit is not None:
                    out.append(
                        Finding(
                            "lock-discipline",
                            path,
                            sub.lineno,
                            sub.col_offset,
                            f"`{name}()` called under a lock blocks "
                            f"inside (.{hit[0]}() at "
                            f"{cand.path}:{hit[1]}) — the critical "
                            "section stalls behind that I/O exactly "
                            "as if it were inline; move the call "
                            "outside the lock",
                        )
                    )
                    break

    # With blocks inside functions: resolved with lexical scope so
    # bare helper calls link right. Module-level withs (no enclosing
    # function) still get direct + attr-helper checks.
    seen: set[int] = set()
    for fi in ctx.graph.functions:
        for node in _walk_shallow(fi.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                seen.add(id(node))
                check_with(node, fi, fi.path)
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, (ast.With, ast.AsyncWith))
                and id(node) not in seen
            ):
                check_with(node, None, mod.path)
    return out


# -- obs-name-drift ---------------------------------------------------

_OBS_KINDS = {"counter", "gauge", "histogram"}
_NAME_RE = re.compile(r"^defer_[a-z0-9_]+$")


def rule_obs_name_drift(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    first_kind: dict[str, tuple[str, str, int]] = {}  # name -> kind,at
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute) and f.attr in _OBS_KINDS
            ):
                continue
            if not node.args or not (
                isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue  # dynamic names can't be checked statically
            name = node.args[0].value
            kind = f.attr
            loc = (mod.path, node.lineno, node.col_offset)
            if not _NAME_RE.match(name):
                out.append(
                    Finding(
                        "obs-name-drift",
                        *loc,
                        f"metric name {name!r} breaks the registry "
                        "convention ^defer_[a-z0-9_]+$ — dashboards "
                        "key on the defer_ prefix",
                    )
                )
            elif kind == "counter" and not name.endswith("_total"):
                out.append(
                    Finding(
                        "obs-name-drift",
                        *loc,
                        f"counter {name!r} must end in _total "
                        "(Prometheus counter convention)",
                    )
                )
            elif kind != "counter" and name.endswith("_total"):
                out.append(
                    Finding(
                        "obs-name-drift",
                        *loc,
                        f"{kind} {name!r} ends in _total, which marks "
                        "counters — rename or change the instrument",
                    )
                )
            seen = first_kind.setdefault(name, (kind, mod.path, node.lineno))
            if seen[0] != kind:
                out.append(
                    Finding(
                        "obs-name-drift",
                        *loc,
                        f"{name!r} registered as a {kind} here but as "
                        f"a {seen[0]} at {seen[1]}:{seen[2]} — one "
                        "name, one instrument kind",
                    )
                )
    return out


RULES: dict[str, Callable[[Context], list[Finding]]] = {
    "host-sync-in-hot-loop": rule_host_sync,
    "fresh-closure-jit": rule_fresh_closure_jit,
    "prng-key-reuse": rule_prng_key_reuse,
    "lock-discipline": rule_lock_discipline,
    "obs-name-drift": rule_obs_name_drift,
}
