"""defer_tpu.analysis — JAX-aware static lint + runtime trace sanitizer.

The repo's worst regressions were silent host/trace hazards: a host
concatenate per decode tick, a fresh-closure jit that re-traced every
call, a full-pool gather hiding inside a correct-looking loop. These
are mechanical staging bugs (the tracing-DSL literature calls them out
— TF Eager, arXiv 1903.01855; Julia→TPU, arXiv 1810.09868), so they
are mechanically detectable.

Two halves:

- Static (AST): ``python -m defer_tpu.analysis --strict defer_tpu/``
  runs five rules over the package (see rules.py) with a lightweight
  call-graph walk that scopes host-sync findings to the serving hot
  paths. Inline escape hatch: ``# analysis: ignore[rule] reason``.
- Runtime: ``sanitizer.trace_sanitizer(*targets)`` counts XLA
  lowerings per jitted callable across a block and raises if anything
  re-traced — the enforcement form of the memo.py discipline.
"""

from defer_tpu.analysis.runner import AnalysisReport, analyze_paths
from defer_tpu.analysis.rules import Finding
from defer_tpu.analysis.sanitizer import RetraceError, trace_sanitizer

__all__ = [
    "AnalysisReport",
    "Finding",
    "RetraceError",
    "analyze_paths",
    "trace_sanitizer",
]
