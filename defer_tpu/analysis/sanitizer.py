"""Runtime half: prove a block of code does not re-trace.

The static rules catch the *patterns* that retrace; this catches the
fact. ``trace_sanitizer(...)`` snapshots the compile-cache size of
every jitted callable it can find in its targets, runs the block, and
raises ``RetraceError`` if anything lowered again — the enforcement
form of PR 3's "warm up, then the tick loop must be trace-stable"
discipline.

    srv = PagedDecodeServer(...)
    ...admit + warmup ticks...
    with trace_sanitizer(srv, defer_tpu.models.gpt) as rep:
        for _ in range(3):
            srv._tick()
    # raises if any step/sampler callable compiled a new variant

Targets may be:
- a jitted callable (anything exposing ``_cache_size()``, which
  jax.jit wrappers do on every jax this repo supports),
- a module (its jitted globals are scanned),
- any object (its attributes are scanned, one level of dict attrs
  included — which picks up the ``_step_cache`` dict that
  utils/memo.cached_step hangs on decoder instances).

Targets are scanned at ``__enter__``: a callable jitted *inside* the
block is by definition a fresh trace and should instead be built in
warmup. Counting uses per-callable cache-size deltas rather than
``jax.monitoring`` events, which fire at varying multiplicity per
compile across jax versions — cache growth is exact.
"""

from __future__ import annotations

import contextlib
import types
from typing import Any, Iterator


class RetraceError(AssertionError):
    """A jitted callable compiled a new variant inside a sanitized
    block. Subclasses AssertionError so pytest reports it as a plain
    test failure, not an error."""


def _cache_size(fn: Any) -> int | None:
    try:
        return int(fn._cache_size())
    except Exception:  # noqa: BLE001 — not a countable jitted callable
        return None


def _is_jitted(obj: Any) -> bool:
    return callable(obj) and _cache_size(obj) is not None


def _scan(targets: tuple[Any, ...]) -> dict[str, Any]:
    found: dict[str, Any] = {}

    def add(label: str, fn: Any) -> None:
        if not any(fn is g for g in found.values()):
            found.setdefault(label, fn)

    for t in targets:
        if _is_jitted(t):
            add(getattr(t, "__name__", repr(t)), t)
        elif isinstance(t, types.ModuleType):
            for k, v in vars(t).items():
                if _is_jitted(v):
                    add(f"{t.__name__}.{k}", v)
        else:
            tname = type(t).__name__
            for k, v in list(getattr(t, "__dict__", {}).items()):
                if _is_jitted(v):
                    add(f"{tname}.{k}", v)
                elif isinstance(v, dict):
                    for kk, vv in v.items():
                        if _is_jitted(vv):
                            add(f"{tname}.{k}[{kk!r}]", vv)
    return found


class TraceReport:
    """Filled in at block exit: what was watched, what re-traced."""

    def __init__(self) -> None:
        self.watched: list[str] = []
        self.deltas: dict[str, int] = {}

    @property
    def retraces(self) -> int:
        return sum(self.deltas.values())


@contextlib.contextmanager
def trace_sanitizer(*targets: Any, allow: int = 0) -> Iterator[TraceReport]:
    """Fail the block if watched jitted callables trace > `allow` new
    variants in total. Raises ValueError when no jitted callable is
    found in `targets` — a sanitizer watching nothing proves nothing."""
    fns = _scan(targets)
    if not fns:
        raise ValueError(
            "trace_sanitizer found no jitted callables in its targets "
            "— pass jitted functions, modules, or warmed-up objects"
        )
    report = TraceReport()
    report.watched = list(fns)
    before = {label: _cache_size(fn) for label, fn in fns.items()}
    try:
        yield report
    finally:
        for label, fn in fns.items():
            after = _cache_size(fn)
            if after is not None and after > before[label]:
                report.deltas[label] = after - before[label]
    if report.retraces > allow:
        detail = ", ".join(
            f"{label}: +{n}" for label, n in sorted(report.deltas.items())
        )
        raise RetraceError(
            f"{report.retraces} retrace(s) inside sanitized block "
            f"(allow={allow}): {detail} — a warmed hot loop must be "
            "trace-stable; see utils/memo.py"
        )
