"""Pass 3 — static perf-contract gate (`perf-contract`).

ROADMAP's hardware-tier item asks for tokens-per-dispatch and
kv-rows-read budget checks "so a future PR can't silently regress the
hot path". This pass makes those budgets DECLARED state instead of
prose: ``budgets.toml`` names each contract, the obs counter that
accounts for it, the hot functions that must feed that counter, and a
numeric bound on a bench-artifact metric. ``defer-analyze --budget
budgets.toml`` then enforces both halves:

Static half (always runs)
    - the contract's counter is registered somewhere in the corpus
      (``reg.counter("defer_..."...)`` with a literal name);
    - every function the contract names exists AND reaches — through
      the same open-world callgraph the host-sync rule uses — at least
      one touch of the counter's pre-bound handle attribute
      (``self.obs.host_dispatches.inc()``). A hot loop that stops
      feeding its accounting counter is exactly the silent-regression
      failure mode: the bench metric would go stale while still
      looking green.

Measured half (when bench data exists)
    - the contract's ``bench_metric`` dotted path is read out of the
      latest ``BENCH_*.json`` (or an explicit ``--bench`` file, or the
      in-memory result dict when bench.py itself calls in) and checked
      against ``max``/``min``. A section the bench round never ran is
      ``no-data`` — only a present-and-violated bound fails, so
      CPU-tier rounds that skip the tp sweep don't fail the gate.

Both halves report through the normal Finding stream (rule
``perf-contract``), so ``--strict --json`` consumers and the bench
extras section see budget state next to lint state.

Python 3.10 has no ``tomllib``; a strict subset parser (tables,
strings, numbers, booleans, flat arrays) backs it so the gate needs
nothing the container doesn't have.
"""

from __future__ import annotations

import ast
import dataclasses
import glob
import json
import os
import re
from typing import Any

from defer_tpu.analysis.rules import Context, Finding

_OBS_KINDS = {"counter", "gauge", "histogram"}


class BudgetError(ValueError):
    """Malformed budgets file: bad TOML, or a contract missing/
    mistyping a required key."""


# -- TOML subset ------------------------------------------------------

_SECTION_RE = re.compile(r"^\[(?P<name>[A-Za-z0-9_.\-]+)\]$")
_KEY_RE = re.compile(r"^(?P<key>[A-Za-z0-9_\-]+)\s*=\s*(?P<val>.+)$")


def _strip_comment(line: str) -> str:
    """Drop a trailing # comment (quote-aware enough for this file's
    grammar: # inside a double-quoted string is kept)."""
    out = []
    in_str = False
    for ch in line:
        if ch == '"':
            in_str = not in_str
        elif ch == "#" and not in_str:
            break
        out.append(ch)
    return "".join(out).strip()


def _parse_value(raw: str, where: str) -> Any:
    raw = raw.strip()
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    if raw in ("true", "false"):
        return raw == "true"
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        if not inner:
            return []
        return [
            _parse_value(part.strip(), where)
            for part in inner.split(",")
            if part.strip()
        ]
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        raise BudgetError(
            f"{where}: unparseable value {raw!r} (the built-in TOML "
            "subset takes strings, numbers, booleans and flat arrays)"
        ) from None


def _parse_toml(text: str, path: str) -> dict[str, Any]:
    """budgets.toml -> nested dict, with a ``__line__`` entry per
    table so findings can point at the contract's declaration."""
    try:
        import tomllib  # Python >= 3.11

        data = tomllib.loads(text)
        # tomllib gives no line info; findings fall back to line 1.
        return data
    except ModuleNotFoundError:
        pass
    except Exception as e:  # malformed under the real parser
        raise BudgetError(f"{path}: {e}") from None
    root: dict[str, Any] = {}
    table = root
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = _strip_comment(raw)
        if not line:
            continue
        m = _SECTION_RE.match(line)
        if m:
            table = root
            for part in m.group("name").split("."):
                table = table.setdefault(part, {})
                if not isinstance(table, dict):
                    raise BudgetError(
                        f"{path}:{lineno}: table {m.group('name')!r} "
                        "collides with a value"
                    )
            table["__line__"] = lineno
            continue
        m = _KEY_RE.match(line)
        if m:
            table[m.group("key")] = _parse_value(
                m.group("val"), f"{path}:{lineno}"
            )
            continue
        raise BudgetError(f"{path}:{lineno}: unparseable line {raw!r}")
    return root


# -- contracts --------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Contract:
    name: str
    counter: str  # obs metric accounting for this contract
    functions: tuple[str, ...]  # hot functions that must feed it
    line: int  # declaration line in budgets.toml (1 if unknown)
    description: str = ""
    max_value: float | None = None  # bound on the bench metric
    min_value: float | None = None
    bench_section: str | None = None  # key in the bench result dict
    bench_metric: str | None = None  # dotted path inside the section


def load_budgets(path: str) -> list[Contract]:
    with open(path, encoding="utf-8") as fh:
        data = _parse_toml(fh.read(), path)
    tables = data.get("contract")
    if not isinstance(tables, dict) or not any(
        isinstance(v, dict) for v in tables.values()
    ):
        raise BudgetError(
            f"{path}: no [contract.<name>] tables — nothing to enforce"
        )
    out: list[Contract] = []
    for name, tab in tables.items():
        if not isinstance(tab, dict):
            continue
        where = f"{path}: [contract.{name}]"
        counter = tab.get("counter")
        if not isinstance(counter, str) or not counter:
            raise BudgetError(f"{where}: missing `counter` (a string)")
        funcs = tab.get("functions")
        if not isinstance(funcs, list) or not all(
            isinstance(f, str) for f in funcs
        ):
            raise BudgetError(
                f"{where}: missing `functions` (array of strings)"
            )
        bounds = {}
        for key in ("max", "min"):
            v = tab.get(key)
            if v is not None and not isinstance(v, (int, float)):
                raise BudgetError(f"{where}: `{key}` must be numeric")
            bounds[key] = float(v) if v is not None else None
        if (
            bounds["max"] is not None or bounds["min"] is not None
        ) and not (
            isinstance(tab.get("bench_section"), str)
            and isinstance(tab.get("bench_metric"), str)
        ):
            raise BudgetError(
                f"{where}: a max/min bound needs `bench_section` and "
                "`bench_metric` naming what it bounds"
            )
        out.append(
            Contract(
                name=name,
                counter=counter,
                functions=tuple(funcs),
                line=int(tab.get("__line__", 1)),
                description=str(tab.get("description", "")),
                max_value=bounds["max"],
                min_value=bounds["min"],
                bench_section=tab.get("bench_section"),
                bench_metric=tab.get("bench_metric"),
            )
        )
    return out


# -- static half ------------------------------------------------------


def _metric_handles(ctx: Context) -> dict[str, set[str]]:
    """metric name -> attribute names its pre-bound handles are stored
    under (``self.host_dispatches = reg.counter("defer_host_..."``
    maps the metric to {"host_dispatches"})."""
    out: dict[str, set[str]] = {}
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            calls = [node.value]
            # handles built in comprehensions/dicts still carry the
            # literal name; find any obs-kind call in the value expr
            calls = [
                c
                for c in ast.walk(node.value)
                if isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr in _OBS_KINDS
            ]
            for call in calls:
                if not (
                    call.args
                    and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)
                ):
                    continue
                metric = call.args[0].value
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute):
                        out.setdefault(metric, set()).add(tgt.attr)
                    elif isinstance(tgt, ast.Name):
                        out.setdefault(metric, set()).add(tgt.id)
    return out


def _touches(fn_node: ast.AST, attrs: set[str]) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Attribute) and node.attr in attrs:
            return True
    return False


def check_static(
    ctx: Context, contracts: list[Contract], budget_path: str
) -> list[Finding]:
    """Registration + reachable-touch checks; findings point at the
    contract declaration in budgets.toml."""
    handles = _metric_handles(ctx)
    out: list[Finding] = []
    for c in contracts:
        attrs = handles.get(c.counter)
        if not attrs:
            out.append(
                Finding(
                    "perf-contract",
                    budget_path,
                    c.line,
                    0,
                    f"[contract.{c.name}] accounts through "
                    f"{c.counter!r} but no analyzed module registers "
                    "that metric — the contract can never be measured",
                )
            )
            continue
        for fname in c.functions:
            cands = ctx.graph.by_name.get(fname, [])
            if not cands:
                out.append(
                    Finding(
                        "perf-contract",
                        budget_path,
                        c.line,
                        0,
                        f"[contract.{c.name}] names hot function "
                        f"{fname!r}, which does not exist in the "
                        "analyzed corpus",
                    )
                )
                continue
            # BFS from the named functions; ANY candidate chain
            # touching the handle satisfies the contract (both decode
            # servers define `_tick`; each feeds the shared metric).
            seen: set[int] = set()
            frontier = list(cands)
            found = False
            while frontier and not found:
                fi = frontier.pop()
                if id(fi.node) in seen:
                    continue
                seen.add(id(fi.node))
                if _touches(fi.node, attrs):
                    found = True
                    break
                for bare, calls in (
                    (True, fi.calls_bare),
                    (False, fi.calls_attr),
                ):
                    for callee in calls:
                        frontier.extend(
                            r
                            for r in ctx.graph.resolve_call(
                                fi, callee, bare
                            )
                            if id(r.node) not in seen
                        )
            if not found:
                out.append(
                    Finding(
                        "perf-contract",
                        budget_path,
                        c.line,
                        0,
                        f"[contract.{c.name}]: nothing reachable from "
                        f"`{fname}` touches the {c.counter!r} handle "
                        f"({'/'.join(sorted(attrs))}) — the hot loop "
                        "stopped feeding its accounting counter, so "
                        "the budget would go stale while looking green",
                    )
                )
    return out


# -- measured half ----------------------------------------------------


def latest_bench_json(search_dir: str = ".") -> tuple[str, dict] | None:
    """Newest BENCH_*.json under `search_dir` (non-recursive), parsed.
    None when there is none or the newest one is unreadable."""
    cands = sorted(
        glob.glob(os.path.join(search_dir, "BENCH_*.json")),
        key=lambda p: (os.path.getmtime(p), p),
    )
    for path in reversed(cands):
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            continue
        if isinstance(data, dict):
            return path, data
    return None


def _bench_sections(data: dict) -> dict:
    """The dict bench sections live in: bench.py's in-memory result
    holds them at top level; the committed round artifacts nest the
    measurement under `parsed`."""
    parsed = data.get("parsed")
    if isinstance(parsed, dict):
        return parsed
    return data


def _navigate(section: Any, dotted: str) -> Any:
    """`windows.8.dispatches_per_token` through a JSON round-trip:
    integer-looking segments try both the int and str key."""
    cur = section
    for part in dotted.split("."):
        if not isinstance(cur, dict):
            return None
        if part in cur:
            cur = cur[part]
            continue
        try:
            ipart = int(part)
        except ValueError:
            return None
        if ipart in cur:
            cur = cur[ipart]
        else:
            return None
    return cur


def evaluate_bench(
    contracts: list[Contract], bench: dict, source: str
) -> list[dict[str, Any]]:
    """Per-contract measured verdicts: status pass|fail|no-data plus
    the observed value and the violated bound, JSON-ready."""
    sections = _bench_sections(bench)
    out: list[dict[str, Any]] = []
    for c in contracts:
        rec: dict[str, Any] = {
            "contract": c.name,
            "counter": c.counter,
            "bench_section": c.bench_section,
            "bench_metric": c.bench_metric,
            "source": source,
            "status": "no-data",
            "value": None,
        }
        if c.bench_section is None or c.bench_metric is None:
            rec["status"] = "static-only"
            out.append(rec)
            continue
        section = sections.get(c.bench_section)
        value = (
            _navigate(section, c.bench_metric)
            if isinstance(section, dict)
            else None
        )
        if not isinstance(value, (int, float)) or isinstance(
            value, bool
        ):
            out.append(rec)
            continue
        rec["value"] = value
        rec["status"] = "pass"
        if c.max_value is not None and value > c.max_value:
            rec["status"] = "fail"
            rec["bound"] = {"max": c.max_value}
        elif c.min_value is not None and value < c.min_value:
            rec["status"] = "fail"
            rec["bound"] = {"min": c.min_value}
        out.append(rec)
    return out


def bench_findings(
    verdicts: list[dict[str, Any]],
    contracts: list[Contract],
    budget_path: str,
) -> list[Finding]:
    by_name = {c.name: c for c in contracts}
    out: list[Finding] = []
    for v in verdicts:
        if v["status"] != "fail":
            continue
        c = by_name[v["contract"]]
        bound_kind, bound_val = next(iter(v["bound"].items()))
        cmp = ">" if bound_kind == "max" else "<"
        out.append(
            Finding(
                "perf-contract",
                budget_path,
                c.line,
                0,
                f"[contract.{c.name}] violated by {v['source']}: "
                f"{c.bench_section}.{c.bench_metric} = {v['value']} "
                f"{cmp} {bound_kind} {bound_val} — the measured hot "
                "path regressed past its declared budget",
            )
        )
    return out
