"""Lightweight name-based call graph for hot-path scoping.

The host-sync rule must only fire inside code that runs per decode
tick / per pipeline step — `np.asarray` in a one-shot admission path
is the sanctioned batched barrier (utils/sync.py), not a regression.
Precise Python call resolution is undecidable; serving loops don't
need it. This graph resolves calls *by name*:

- ``self.f(...)`` / ``obj.f(...)`` / ``f(...)`` all link to every
  analyzed function or method named ``f``.

That open-world rule over-approximates (recall over precision — a
missed hot function is a missed hazard, a spurious edge at worst asks
for one justified ignore), and it is robust to the repo's style of
passing callables around (builders, samplers, sync hooks).

Hot set = everything reachable from the serving roots: ``_tick``
(both decode servers), ``generate`` / ``speculative_generate`` (model
decode loops), ``stream`` / ``throughput`` / ``_stream_loop`` (the
pipeline step loops; ``run_defer`` itself is construction, its loop
half is the root).
"""

from __future__ import annotations

import ast
import dataclasses

DEFAULT_ROOTS = (
    "_tick",
    "generate",
    "speculative_generate",
    "stream",
    "throughput",
    "_stream_loop",
)

# Attribute calls to these names resolve to dict/queue/socket methods
# far more often than to repo functions; linking them would mark the
# whole codebase hot through e.g. `input_stream.get()` →
# `KerasWeights.get`. Bare-name calls still resolve normally.
_GENERIC_ATTRS = frozenset(
    "get put set add pop update append extend clear copy close items "
    "keys values read write flush start run acquire release encode "
    "decode strip format sort index count insert remove".split()
)


@dataclasses.dataclass
class FuncInfo:
    name: str  # bare name ("_tick")
    qualname: str  # "runtime/paged.py:PagedDecodeServer._tick"
    path: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    # Bare `f(...)` calls are lexically scoped: they resolve within the
    # same module, plus corpus-wide for names this module from-imports.
    # `obj.f(...)` attribute calls resolve corpus-wide (methods cross
    # modules through dispatch), minus _GENERIC_ATTRS.
    calls_bare: set[str] = dataclasses.field(default_factory=set)
    calls_attr: set[str] = dataclasses.field(default_factory=set)
    # Enclosing FUNCTION name chain at the def site, outermost first;
    # () for module-level functions and class methods. A nested def is
    # only bare-callable where it is lexically visible, and is never a
    # valid `obj.name(...)` target — both resolutions use this.
    scope: tuple[str, ...] = ()
    # Innermost enclosing CLASS at the def site (None for plain
    # functions). `self.x` writes in a method mutate an instance of
    # this class — the race detector keys shared state on it.
    owner_class: str | None = None

    @property
    def in_function(self) -> bool:
        return bool(self.scope)


@dataclasses.dataclass(frozen=True)
class ThreadSite:
    """One ``threading.Thread(target=...)`` spawn: a thread edge. The
    target function runs on a NEW thread, so the hot set must not flow
    through it, but the mutation-domain pass (domains.py) roots a
    thread domain at every resolvable target."""

    path: str
    line: int
    target_bare: str | None  # Thread(target=feed)
    target_attr: str | None  # Thread(target=self._drain_loop)
    thread_name: str | None  # the name= kwarg, when a string literal
    in_func: str | None  # bare name of the spawning function


def _thread_target(call: ast.Call) -> tuple[str | None, str | None] | None:
    """(bare, attr) target names of a threading.Thread(...) call, or
    None if this call is not a Thread construction / has no target."""
    f = call.func
    name = (
        f.id if isinstance(f, ast.Name)
        else f.attr if isinstance(f, ast.Attribute)
        else None
    )
    if name != "Thread":
        return None
    tgt = next(
        (k.value for k in call.keywords if k.arg == "target"), None
    )
    if isinstance(tgt, ast.Name):
        return tgt.id, None
    if isinstance(tgt, ast.Attribute):
        return None, tgt.attr
    return None


class _Collector(ast.NodeVisitor):
    def __init__(
        self,
        path: str,
        out: list[FuncInfo],
        threads: list[ThreadSite] | None = None,
    ):
        self.path = path
        self.out = out
        self.threads = threads if threads is not None else []
        self.stack: list[str] = []  # class/function name chain
        self.kinds: list[str] = []  # "class" | "func", parallel to stack

    def _visit_func(self, node: ast.AST) -> None:
        qual = ".".join([*self.stack, node.name])
        classes = [
            n for n, k in zip(self.stack, self.kinds) if k == "class"
        ]
        info = FuncInfo(
            name=node.name,
            qualname=f"{self.path}:{qual}",
            path=self.path,
            node=node,
            scope=tuple(
                n
                for n, k in zip(self.stack, self.kinds)
                if k == "func"
            ),
            owner_class=classes[-1] if classes else None,
        )
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Name):
                    info.calls_bare.add(f.id)
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr not in _GENERIC_ATTRS
                ):
                    info.calls_attr.add(f.attr)
                # shard_map(body, mesh, ...) runs `body` per tick just
                # as surely as body() would: link the wrapped function
                # so the hot set flows THROUGH the wrapper into the
                # sharded tick bodies. Keyed on the `shard_map` name
                # alone — generic function-valued arguments (e.g.
                # lax.scan bodies) must NOT create edges (the
                # window_scan fixtures pin that).
                callee = (
                    f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute)
                    else None
                )
                if callee == "shard_map":
                    tgt = sub.args[0] if sub.args else next(
                        (k.value for k in sub.keywords if k.arg == "f"),
                        None,
                    )
                    if isinstance(tgt, ast.Name):
                        info.calls_bare.add(tgt.id)
                    elif (
                        isinstance(tgt, ast.Attribute)
                        and tgt.attr not in _GENERIC_ATTRS
                    ):
                        info.calls_attr.add(tgt.attr)
        self.out.append(info)
        self.stack.append(node.name)
        self.kinds.append("func")
        self.generic_visit(node)
        self.stack.pop()
        self.kinds.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.kinds.append("class")
        self.generic_visit(node)
        self.stack.pop()
        self.kinds.pop()

    def visit_Call(self, node: ast.Call) -> None:
        tgt = _thread_target(node)
        if tgt is not None:
            name_kw = next(
                (k.value for k in node.keywords if k.arg == "name"),
                None,
            )
            funcs = [
                n for n, k in zip(self.stack, self.kinds) if k == "func"
            ]
            self.threads.append(
                ThreadSite(
                    path=self.path,
                    line=node.lineno,
                    target_bare=tgt[0],
                    target_attr=tgt[1],
                    thread_name=(
                        name_kw.value
                        if isinstance(name_kw, ast.Constant)
                        and isinstance(name_kw.value, str)
                        else None
                    ),
                    in_func=funcs[-1] if funcs else None,
                )
            )
        self.generic_visit(node)


class CallGraph:
    """Functions of the analyzed file set + name-resolved call edges."""

    def __init__(self) -> None:
        self.functions: list[FuncInfo] = []
        self.by_name: dict[str, list[FuncInfo]] = {}
        self.imports: dict[str, set[str]] = {}  # path -> imported names
        self.thread_sites: list[ThreadSite] = []

    def add_module(self, path: str, tree: ast.AST) -> None:
        found: list[FuncInfo] = []
        _Collector(path, found, self.thread_sites).visit(tree)
        self.functions.extend(found)
        for fi in found:
            self.by_name.setdefault(fi.name, []).append(fi)
        names = self.imports.setdefault(path, set())
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                names.update(a.asname or a.name for a in node.names)

    def _resolve(self, fi: FuncInfo, callee: str, bare: bool):
        cands = self.by_name.get(callee, [])
        if bare:
            chain = (*fi.scope, fi.name)
            out = [
                c
                for c in cands
                if c.path == fi.path
                and c.scope == chain[: len(c.scope)]
            ]
            if callee in self.imports.get(fi.path, ()):
                out += [
                    c
                    for c in cands
                    if c.path != fi.path and not c.in_function
                ]
            return out
        return [c for c in cands if not c.in_function]

    def resolve_call(
        self, fi: FuncInfo | None, callee: str, bare: bool
    ) -> list[FuncInfo]:
        """Public name resolution for passes that walk call sites
        themselves (lock-discipline helper lookup, domain propagation).
        `fi` scopes bare-call resolution; None means module-level
        resolution is impossible, so only corpus-wide attr resolution
        applies. Generic attr names (get/put/start/...) resolve to
        nothing, same as edge collection."""
        if not bare and callee in _GENERIC_ATTRS:
            return []
        if fi is None:
            if bare:
                return []
            return [
                c
                for c in self.by_name.get(callee, [])
                if not c.in_function
            ]
        return self._resolve(fi, callee, bare)

    def resolve_thread_target(self, site: ThreadSite) -> list[FuncInfo]:
        """FuncInfos a Thread(target=...) site may start. Bare targets
        resolve within the spawning module (plus from-imports, e.g.
        Thread(target=serve_prefill)); attr targets corpus-wide."""
        if site.target_bare is not None:
            cands = self.by_name.get(site.target_bare, [])
            out = [c for c in cands if c.path == site.path]
            if site.target_bare in self.imports.get(site.path, ()):
                out += [
                    c
                    for c in cands
                    if c.path != site.path and not c.in_function
                ]
            return out
        if site.target_attr is not None:
            cands = [
                c
                for c in self.by_name.get(site.target_attr, [])
                if not c.in_function
            ]
            # `Thread(target=self._drain_loop)` names a method of the
            # spawning class — prefer same-module candidates and only
            # fall back corpus-wide when the module defines none, so a
            # common method name (`_loop`) doesn't seed a thread
            # domain on every unrelated class that uses it.
            local = [c for c in cands if c.path == site.path]
            return local or cands
        return []

    def hot_set(self, roots: tuple[str, ...] = DEFAULT_ROOTS) -> set[int]:
        """ids of FuncInfo.node for every function reachable by name
        from any root. Nested defs are separate nodes: a closure is hot
        only if something hot calls it by name."""
        seen: set[int] = set()
        frontier = [fi for r in roots for fi in self.by_name.get(r, [])]
        while frontier:
            fi = frontier.pop()
            if id(fi.node) in seen:
                continue
            seen.add(id(fi.node))
            for bare, calls in (
                (True, fi.calls_bare),
                (False, fi.calls_attr),
            ):
                for callee in calls:
                    frontier.extend(
                        c
                        for c in self._resolve(fi, callee, bare)
                        if id(c.node) not in seen
                    )
        return seen
