"""Inline suppression comments: ``# analysis: ignore[rule] reason``.

Grammar (one comment suppresses one line):

    x = np.asarray(y)  # analysis: ignore[host-sync-in-hot-loop] final drain
    # analysis: ignore[lock-discipline] frame writes must serialize
    self._sock.sendall(buf)

- ``ignore[a, b]`` lists the rules it silences; ``ignore`` with no
  bracket silences every rule (discouraged — strict mode wants intent).
- A trailing comment covers its own line; a comment alone on a line
  covers the next CODE line — intervening comment/blank lines don't
  break the link, so justifications may wrap over several lines.
- Everything after the bracket is the justification. ``--strict``
  treats a reason-less ignore as a finding itself: the escape hatch
  must document why the hazard is safe, not just mute it.

Comments are found with `tokenize`, not a regex over raw lines, so a
string literal containing the marker text can never suppress anything.
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize

_MARKER = re.compile(
    r"#\s*analysis:\s*ignore"
    r"(?:\[(?P<rules>[a-z0-9_\-,\s]*)\])?"
    r"\s*[-—:]*\s*(?P<reason>.*)$"
)


@dataclasses.dataclass(frozen=True)
class Ignore:
    line: int  # the source line this ignore suppresses
    comment_line: int  # where the comment itself lives
    rules: frozenset[str]  # empty = suppress all rules
    reason: str

    def covers(self, rule: str) -> bool:
        return not self.rules or rule in self.rules


class IgnoreMap:
    """All ignore comments of one file, queryable by (rule, line)."""

    def __init__(self, source: str):
        self.ignores: list[Ignore] = []
        self._by_line: dict[int, list[Ignore]] = {}
        try:
            tokens = list(
                tokenize.generate_tokens(io.StringIO(source).readline)
            )
        except (tokenize.TokenError, SyntaxError, IndentationError):
            return  # unparsable files are reported by the runner anyway
        lines = source.splitlines()
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _MARKER.match(tok.string)
            if m is None:
                continue
            rules = frozenset(
                r.strip()
                for r in (m.group("rules") or "").split(",")
                if r.strip()
            )
            row, col = tok.start
            own_line = not lines[row - 1][:col].strip()
            target = row
            if own_line:
                # Next code line: skip the justification's own wrapped
                # comment lines and any blanks.
                target = row + 1
                while target <= len(lines):
                    text = lines[target - 1].strip()
                    if text and not text.startswith("#"):
                        break
                    target += 1
            ign = Ignore(
                line=target,
                comment_line=row,
                rules=rules,
                reason=m.group("reason").strip(),
            )
            self.ignores.append(ign)
            self._by_line.setdefault(ign.line, []).append(ign)

    def match(self, rule: str, line: int) -> Ignore | None:
        for ign in self._by_line.get(line, ()):
            if ign.covers(rule):
                return ign
        return None
