import sys

from defer_tpu.analysis.runner import main

if __name__ == "__main__":
    sys.exit(main())
