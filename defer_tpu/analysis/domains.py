"""Pass 1 — mutation-domain race detector (`cross-domain-write`).

The serving stack's thread story is a set of single-writer invariants
that used to live only in prose: ARCHITECTURE.md and module docstrings
say the pool, the radix `PrefixBlockCache`, `SlotSampler` rows and the
fleet adverts are touched by exactly one thread, while drain/transport
threads only ever park work in queues for the serving thread to pump.
This pass turns that prose into a checked contract.

Model
-----
Every function gets a set of THREAD DOMAINS — names for "which thread
runs this":

- the serving roots (callgraph.DEFAULT_ROOTS) seed domain ``serving``;
- an annotation comment on (or immediately above) a ``def`` pins a
  domain explicitly::

      # analysis: domain(drain) device->host copies live here
      def _drain_loop(self):

- any function passed as ``threading.Thread(target=...)`` that carries
  no annotation is inferred to start its OWN domain, named after the
  Thread's ``name=`` kwarg when that is a string literal (else
  ``thread:<funcname>``) — a conservative default that forces either an
  annotation or a justification the first time it shares state;
- domains flow through the same open-world callgraph the host-sync
  rule uses. An annotated function is a propagation barrier: its
  declared domain wins over whatever domain its callers run in.

``domain(any)`` marks a function whose writes are deliberately
cross-thread-safe (a test seam, an Event-mediated handoff); its writes
never count toward a race.

Finding
-------
For every ``self``-rooted attribute/subscript write (``self.x = ...``,
``self.x[i] = ...``, ``self.x += ...``) outside ``__init__``, writes to
the same (class, attribute) slot are grouped. If the writers span two
or more concrete domains, each write NOT lexically inside a
``with <lock>:`` block is flagged. Queue ``put``/``get`` and Event
``set`` are method calls, not attribute writes, so the sanctioned
park/pump handoff pattern (disagg/ingest.py, `HostKVSpill`) is clean
by construction — exactly the point of the convention.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Iterator

from defer_tpu.analysis.callgraph import FuncInfo
from defer_tpu.analysis.rules import (
    RULES,
    Context,
    Finding,
    _FUNC_NODES,
    _mentions_lock,
)

SERVING_DOMAIN = "serving"
ANY_DOMAIN = "any"

_DOMAIN_MARKER = re.compile(
    r"#\s*analysis:\s*domain\(\s*(?P<name>[a-z0-9_\-:]+)\s*\)"
    r"\s*[-—:]*\s*(?P<reason>.*)$"
)

_CONSTRUCTORS = {"__init__", "__post_init__", "__new__"}


@dataclasses.dataclass(frozen=True)
class DomainAnnot:
    line: int  # the code line (def line) this annotation covers
    domain: str
    reason: str


class DomainMap:
    """All ``# analysis: domain(...)`` annotations of one file,
    attached the same way ignore.py attaches suppressions: a trailing
    comment covers its own line, a comment alone on a line covers the
    next code line (comment/blank lines between don't break the
    link)."""

    def __init__(self, source: str):
        self.by_line: dict[int, DomainAnnot] = {}
        try:
            tokens = list(
                tokenize.generate_tokens(io.StringIO(source).readline)
            )
        except (tokenize.TokenError, SyntaxError, IndentationError):
            return
        lines = source.splitlines()
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _DOMAIN_MARKER.match(tok.string)
            if m is None:
                continue
            row, col = tok.start
            target = row
            if not lines[row - 1][:col].strip():
                target = row + 1
                while target <= len(lines):
                    text = lines[target - 1].strip()
                    if text and not text.startswith("#"):
                        break
                    target += 1
            self.by_line[target] = DomainAnnot(
                line=target,
                domain=m.group("name"),
                reason=m.group("reason").strip(),
            )


@dataclasses.dataclass(frozen=True)
class _Write:
    attr: str  # dotted chain after self: "slots", "radix.lru"
    line: int
    col: int
    locked: bool


def _self_chain(node: ast.AST) -> str | None:
    """Dotted attribute chain rooted at `self` for a write target:
    `self.slots[i]` -> "slots", `self._store` -> "_store",
    `self.radix.generation` -> "radix.generation". None for anything
    not rooted at a bare `self` name."""
    while isinstance(node, ast.Subscript):
        node = node.value
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return ".".join(reversed(parts))
    return None


def _write_targets(stmt: ast.AST) -> Iterator[ast.AST]:
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                yield from tgt.elts
            else:
                yield tgt
    elif isinstance(stmt, ast.AugAssign):
        yield stmt.target
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        yield stmt.target


def _collect_writes(fn_node: ast.AST) -> list[_Write]:
    """Self-rooted writes of one function body (nested defs are their
    own analysis units), each tagged with whether a lock-mentioning
    `with` block encloses it lexically."""
    out: list[_Write] = []

    def walk(node: ast.AST, locked: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                continue
            inner = locked
            if isinstance(child, (ast.With, ast.AsyncWith)) and any(
                _mentions_lock(item.context_expr)
                for item in child.items
            ):
                inner = True
            for tgt in _write_targets(child):
                chain = _self_chain(tgt)
                if chain is not None:
                    out.append(
                        _Write(
                            chain, child.lineno, child.col_offset, inner
                        )
                    )
            walk(child, inner)

    walk(fn_node, False)
    return out


def _annot_for(
    annots: dict[str, DomainMap], fi: FuncInfo
) -> DomainAnnot | None:
    dm = annots.get(fi.path)
    if dm is None:
        return None
    return dm.by_line.get(fi.node.lineno)


def infer_domains(
    ctx: Context, annots: dict[str, DomainMap]
) -> dict[int, set[str]]:
    """id(FuncInfo.node) -> set of thread domains that reach it."""
    graph = ctx.graph
    domains: dict[int, set[str]] = {}
    entries: list[tuple[FuncInfo, str]] = []

    annotated: set[int] = set()
    for fi in graph.functions:
        ann = _annot_for(annots, fi)
        if ann is not None:
            annotated.add(id(fi.node))
            entries.append((fi, ann.domain))

    for root in ctx.roots:
        for fi in graph.by_name.get(root, []):
            if id(fi.node) not in annotated:
                entries.append((fi, SERVING_DOMAIN))

    for site in graph.thread_sites:
        for fi in graph.resolve_thread_target(site):
            if id(fi.node) in annotated:
                continue
            inferred = site.thread_name or f"thread:{fi.name}"
            entries.append((fi, inferred))

    for entry, dom in entries:
        frontier = [entry]
        while frontier:
            fi = frontier.pop()
            seen = domains.setdefault(id(fi.node), set())
            if dom in seen:
                continue
            seen.add(dom)
            for bare, calls in (
                (True, fi.calls_bare),
                (False, fi.calls_attr),
            ):
                for callee in calls:
                    for c in graph.resolve_call(fi, callee, bare):
                        # Annotated callees keep their declared
                        # domain — the annotation is a barrier.
                        if id(c.node) in annotated:
                            continue
                        if dom not in domains.get(id(c.node), ()):
                            frontier.append(c)
    return domains


def rule_cross_domain_write(ctx: Context) -> list[Finding]:
    annots = {m.path: DomainMap(m.source) for m in ctx.modules}
    domains = infer_domains(ctx, annots)

    # (class, attr-chain) -> [(write, fi, writer-domains)]
    groups: dict[
        tuple[str, str], list[tuple[_Write, FuncInfo, set[str]]]
    ] = {}
    for fi in ctx.graph.functions:
        if fi.owner_class is None or fi.name in _CONSTRUCTORS:
            continue
        doms = domains.get(id(fi.node))
        if not doms:
            continue  # unreachable from any entry: unattributable
        for w in _collect_writes(fi.node):
            groups.setdefault((fi.owner_class, w.attr), []).append(
                (w, fi, doms)
            )

    out: list[Finding] = []
    for (cls, attr), writers in groups.items():
        concrete: set[str] = set()
        for _, _, doms in writers:
            concrete |= doms - {ANY_DOMAIN}
        if len(concrete) < 2:
            continue
        for w, fi, doms in writers:
            own = doms - {ANY_DOMAIN}
            if not own or w.locked:
                continue
            others = sorted(concrete - own)
            if not others:
                continue  # every foreign writer was domain(any)
            other_site = next(
                (
                    f"{ofi.path}:{ow.line}"
                    for ow, ofi, odoms in writers
                    if (odoms - {ANY_DOMAIN}) - own
                ),
                "elsewhere",
            )
            out.append(
                Finding(
                    "cross-domain-write",
                    fi.path,
                    w.line,
                    w.col,
                    f"`self.{attr}` ({cls}) is written here in "
                    f"domain({'/'.join(sorted(own))}) and from "
                    f"domain({'/'.join(others)}) at {other_site} "
                    "with no lock held — single-writer invariant "
                    "broken; take the lock, hand off through a "
                    "park/pump queue, or annotate the entry points "
                    "(# analysis: domain(...)) / justify with an "
                    "ignore",
                )
            )
    return out


# Registration lives with the rule (rules.py's convention); runner.py
# imports this module so the pass is always on.
RULES["cross-domain-write"] = rule_cross_domain_write
