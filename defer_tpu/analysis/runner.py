"""Corpus collection, rule dispatch, suppression, CLI.

``python -m defer_tpu.analysis --strict defer_tpu/`` is part of the
tier-1 verify recipe (ROADMAP.md): exit 0 means every rule is clean or
carries a justified inline ignore. The obs registry gets
``defer_analysis_findings_total{rule=...}`` so bench extras and
``--json`` consumers can track finding counts over time (0 in CI).
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import sys
from typing import Any, Sequence

from defer_tpu.analysis.callgraph import DEFAULT_ROOTS, CallGraph
from defer_tpu.analysis.ignore import Ignore, IgnoreMap
from defer_tpu.analysis.rules import RULES, Context, Finding, Module

# Self-registering passes: importing them adds their rules to RULES
# (cross-domain-write, shard-spec). The budget pass is not a RULES
# entry — it only runs when --budget names a contracts file.
import defer_tpu.analysis.domains  # noqa: E402,F401
import defer_tpu.analysis.shardcheck  # noqa: E402,F401
from defer_tpu.analysis.budget import (  # noqa: E402
    BudgetError,
    bench_findings,
    check_static,
    evaluate_bench,
    latest_bench_json,
    load_budgets,
)


@dataclasses.dataclass
class AnalysisReport:
    findings: list[Finding]  # active (unsuppressed) findings
    suppressed: list[tuple[Finding, Ignore]]
    files: int
    # Per-contract verdicts when the run carried a budgets file
    # ({"path": ..., "bench": ..., "contracts": [...]}); None otherwise.
    budget: dict[str, Any] | None = None

    @property
    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    @property
    def suppressed_by_rule(self) -> dict[str, int]:
        """Suppression counts per rule — the growth signal --strict
        prints so an ignore-sprawl trend is visible in CI output."""
        out: dict[str, int] = {}
        for f, _ in self.suppressed:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self) -> dict[str, Any]:
        out = {
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "counts": self.counts,
            "suppressed": len(self.suppressed),
            "suppressed_by_rule": self.suppressed_by_rule,
            "files": self.files,
        }
        if self.budget is not None:
            out["budget"] = self.budget
        return out


def _collect_files(paths: Sequence[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                files.extend(
                    os.path.join(root, n)
                    for n in names
                    if n.endswith(".py")
                )
        else:
            files.append(p)
    return sorted(set(files))


def analyze_paths(
    paths: Sequence[str],
    *,
    rules: Sequence[str] | None = None,
    roots: Sequence[str] = DEFAULT_ROOTS,
    strict: bool = False,
    budget: str | None = None,
    bench: str | dict | None = None,
) -> AnalysisReport:
    """Run the (selected) rules over every .py file under `paths`.

    `budget` names a contracts file (budgets.toml) to enforce; `bench`
    optionally supplies measured numbers for its cross-check — a path
    to a BENCH_*.json, or the in-memory result dict when bench.py
    calls in on itself. With `budget` set and `bench` unset, the
    newest BENCH_*.json in the current directory is used when present.
    Raises BudgetError (a ValueError) on a malformed contracts file.
    """
    unknown = set(rules or ()) - set(RULES)
    if unknown:
        raise ValueError(f"unknown rules: {sorted(unknown)}")
    modules: list[Module] = []
    ignores: dict[str, IgnoreMap] = {}
    raw: list[Finding] = []
    files = _collect_files(paths)
    graph = CallGraph()
    for path in files:
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError, ValueError) as e:
            raw.append(Finding("parse-error", path, 1, 0, str(e)))
            continue
        modules.append(Module(path, source, tree))
        ignores[path] = IgnoreMap(source)
        graph.add_module(path, tree)
    ctx = Context(modules, graph, tuple(roots))
    for name, fn in RULES.items():
        if rules and name not in rules:
            continue
        raw.extend(fn(ctx))

    budget_state: dict[str, Any] | None = None
    if budget is not None:
        contracts = load_budgets(budget)  # raises BudgetError
        raw.extend(check_static(ctx, contracts, budget))
        bench_data: dict | None = None
        source_name = ""
        if isinstance(bench, dict):
            bench_data, source_name = bench, "<in-memory bench result>"
        elif isinstance(bench, str):
            with open(bench, encoding="utf-8") as fh:
                bench_data = json.load(fh)
            source_name = bench
        else:
            found = latest_bench_json(".")
            if found is not None:
                source_name, bench_data = found
        verdicts = (
            evaluate_bench(contracts, bench_data, source_name)
            if bench_data is not None
            else evaluate_bench(contracts, {}, "<no bench data>")
        )
        raw.extend(bench_findings(verdicts, contracts, budget))
        budget_state = {
            "path": budget,
            "bench": source_name or None,
            "contracts": verdicts,
        }

    active: list[Finding] = []
    suppressed: list[tuple[Finding, Ignore]] = []
    for f in raw:
        imap = ignores.get(f.path)
        ign = imap.match(f.rule, f.line) if imap else None
        if ign is None:
            active.append(f)
        elif strict and not ign.reason:
            # Strict tier: the escape hatch must say WHY.
            active.append(
                dataclasses.replace(
                    f,
                    rule="ignore-without-reason",
                    message=(
                        f"ignore[{f.rule}] suppresses a finding but "
                        "gives no justification — add a reason after "
                        "the bracket"
                    ),
                )
            )
        else:
            suppressed.append((f, ign))
    active.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return AnalysisReport(active, suppressed, len(modules), budget_state)


def record_findings(report: AnalysisReport, registry: Any = None) -> None:
    """Publish per-rule finding counts to the obs registry (0 in CI;
    bench extras and --json consumers watch the trend)."""
    from defer_tpu.obs.metrics import get_registry

    reg = registry if registry is not None else get_registry()
    counts = report.counts
    for rule in list(RULES) + sorted(set(counts) - set(RULES)):
        reg.counter(
            "defer_analysis_findings_total",
            "Unsuppressed static-analysis findings, by rule",
            {"rule": rule},
        ).inc(counts.get(rule, 0))


def main(argv: Sequence[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="defer-analyze",
        description=(
            "JAX-aware static lint for defer_tpu: host syncs on hot "
            "paths, fresh-closure jit, PRNG key reuse, lock "
            "discipline, obs naming"
        ),
    )
    ap.add_argument(
        "paths", nargs="*", default=["defer_tpu"],
        help="files or directories to analyze (default: defer_tpu)",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="also fail on ignore comments without a justification",
    )
    ap.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit a single JSON object instead of text findings",
    )
    ap.add_argument(
        "--rules", default=None,
        help=f"comma list to run a subset of {', '.join(RULES)}",
    )
    ap.add_argument(
        "--roots", default=None,
        help=(
            "comma list of hot-path root function names "
            f"(default: {', '.join(DEFAULT_ROOTS)})"
        ),
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print rule names and exit",
    )
    ap.add_argument(
        "--budget", default=None, metavar="BUDGETS_TOML",
        help=(
            "enforce the perf contracts declared in this file "
            "(static counter-touch checks always; measured bounds "
            "against --bench or the newest BENCH_*.json in cwd)"
        ),
    )
    ap.add_argument(
        "--bench", default=None, metavar="BENCH_JSON",
        help="bench artifact for the --budget measured cross-check",
    )
    args = ap.parse_args(argv)
    if args.list_rules:
        print("\n".join(RULES))
        return 0
    try:
        report = analyze_paths(
            args.paths,
            rules=args.rules.split(",") if args.rules else None,
            roots=(
                tuple(args.roots.split(",")) if args.roots
                else DEFAULT_ROOTS
            ),
            strict=args.strict,
            budget=args.budget,
            bench=args.bench,
        )
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        record_findings(report)
    except Exception:  # noqa: BLE001 — lint must not die on obs wiring
        pass
    if args.as_json:
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
    else:
        for f in report.findings:
            print(f.format())
        if args.strict and report.suppressed:
            # The ignore ledger: per-rule suppression counts, so CI
            # output shows growth even while the gate stays green.
            print("suppressions by rule:", file=sys.stderr)
            for rule, n in sorted(report.suppressed_by_rule.items()):
                print(f"  {rule:24s} {n:3d}", file=sys.stderr)
        if report.budget is not None:
            bench_src = report.budget["bench"] or "none found"
            print(f"budget: {report.budget['path']} "
                  f"(bench: {bench_src})", file=sys.stderr)
            for v in report.budget["contracts"]:
                val = "" if v["value"] is None else f" = {v['value']}"
                print(
                    f"  {v['contract']:28s} {v['status']}{val}",
                    file=sys.stderr,
                )
        print(
            f"{len(report.findings)} finding(s), "
            f"{len(report.suppressed)} suppressed, "
            f"{report.files} file(s) analyzed",
            file=sys.stderr,
        )
    return 1 if report.findings else 0
