"""Pass 2 — shard_map spec consistency (`shard-spec`).

Every hot tick body runs under ``shard_map`` with hand-maintained
specs, and nothing but discipline keeps those specs aligned with the
body signatures, the mesh axes and the collective accounting. Four
statically checkable contracts:

1. **Arity**: ``in_specs`` is positional — a tuple with one entry per
   body parameter. The tuple length is computed for literal-ish
   expressions (``(a, b, c) + (r,) * 11``) and compared against the
   body's signature when the body resolves to a local ``def`` or
   lambda. A mismatch traces as a confusing pytree error at runtime;
   here it is one line.
2. **Axis names**: ``PartitionSpec("model")`` names an axis that must
   exist on the mesh. When the mesh is constructed nearby from
   literal axis names (``Mesh(devs, ("model",))``,
   ``make_mesh({"model": 2}, ...)``), the axis sets are compared;
   dynamic meshes (``self.mesh``) are skipped, fixtures pin the check.
3. **check_rep=False**: disabling the replication checker is
   sometimes required (a body ending in a tiled all_gather the checker
   can't infer) but never free — each such site must carry a justified
   ``# analysis: ignore[shard-spec] reason`` on the ``check_rep`` line,
   the same escape-hatch discipline every other rule uses.
4. **psum mirror**: the host-side ``defer_tp_psum_total`` counter is
   driven by a mirror constant (``_psums_per_fwd = A * num_layers +
   B`` in runtime/paged.py). The pass re-derives A and B from the
   jitted bodies — A = branch-collapsed ``lax.psum`` sites across the
   per-layer trio ``_block``/``_attn_qkv``/``_attn_out``, B = psum
   sites in ``embed_lookup`` plus ``all_gather`` sites in
   ``_replicate_logits`` — and flags the mirror when the code moved
   out from under it. (Branch-collapsed: exclusive if/else arms count
   once, an early-``return`` arm does not see later sites.)
"""

from __future__ import annotations

import ast
from typing import Iterator

from defer_tpu.analysis.callgraph import FuncInfo
from defer_tpu.analysis.rules import (
    RULES,
    Context,
    Finding,
    _FUNC_NODES,
)

_SPEC_NAMES = {"P", "PSpec", "PartitionSpec"}

# The psum-mirror convention (check 4): mirror attribute, the
# per-layer functions whose psum sites the A coefficient counts, and
# the per-forward constant functions for B.
MIRROR_ATTR = "_psums_per_fwd"
PER_LAYER_FUNCS = ("_block", "_attn_qkv", "_attn_out")
CONST_PSUM_FUNC = "embed_lookup"
CONST_GATHER_FUNC = "_replicate_logits"


def _callee_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _kwarg(call: ast.Call, name: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _spec_tuple_len(expr: ast.AST) -> int | None:
    """Statically computable length of an in_specs expression:
    literal tuples, + concatenation, and tuple * <int literal>."""
    if isinstance(expr, (ast.Tuple, ast.List)):
        if any(isinstance(e, ast.Starred) for e in expr.elts):
            return None
        return len(expr.elts)
    if isinstance(expr, ast.BinOp):
        if isinstance(expr.op, ast.Add):
            left = _spec_tuple_len(expr.left)
            right = _spec_tuple_len(expr.right)
            if left is None or right is None:
                return None
            return left + right
        if isinstance(expr.op, ast.Mult):
            for tup, n in (
                (expr.left, expr.right),
                (expr.right, expr.left),
            ):
                tl = _spec_tuple_len(tup)
                if (
                    tl is not None
                    and isinstance(n, ast.Constant)
                    and isinstance(n.value, int)
                ):
                    return tl * n.value
    return None


def _positional_arity(node: ast.AST) -> int | None:
    """Positional parameter count of a def/lambda; None when *args
    makes the arity open."""
    a = node.args
    if a.vararg is not None:
        return None
    return len(a.posonlyargs) + len(a.args)


def _resolve_body(
    ctx: Context, fi: FuncInfo | None, expr: ast.AST
) -> ast.AST | None:
    """The def/lambda node a shard_map body expression names, when
    that is decidable: an inline lambda, or a Name resolving to
    exactly one lexically visible function (the innermost match)."""
    if isinstance(expr, ast.Lambda):
        return expr
    if not isinstance(expr, ast.Name) or fi is None:
        return None
    chain = (*fi.scope, fi.name)
    cands = [
        c
        for c in ctx.graph.by_name.get(expr.id, [])
        if c.path == fi.path and c.scope == chain[: len(c.scope)]
    ]
    if not cands:
        return None
    deepest = max(len(c.scope) for c in cands)
    cands = [c for c in cands if len(c.scope) == deepest]
    return cands[0].node if len(cands) == 1 else None


def _axis_names_used(expr: ast.AST) -> Iterator[tuple[str, ast.AST]]:
    """String-literal axis names inside PartitionSpec(...) calls of a
    specs expression (dynamic entries are silently unknowable)."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        if _callee_name(node) not in _SPEC_NAMES:
            continue
        for arg in node.args:
            elts = (
                arg.elts
                if isinstance(arg, (ast.Tuple, ast.List))
                else [arg]
            )
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(
                    e.value, str
                ):
                    yield e.value, node


def _literal_axes(expr: ast.AST) -> frozenset[str] | None:
    """Axis names of a mesh-constructing expression, when literal:
    Mesh(devs, ("a", "b")), Mesh(devs, axis_names=(...)),
    make_mesh({"a": 2}, ...), jax.make_mesh((2,), ("a",))."""
    if not isinstance(expr, ast.Call):
        return None
    name = _callee_name(expr)
    cand: ast.AST | None = None
    if name == "Mesh":
        cand = _kwarg(expr, "axis_names")
        if cand is None and len(expr.args) >= 2:
            cand = expr.args[1]
    elif name == "make_mesh":
        cand = _kwarg(expr, "axis_names")
        if cand is None and expr.args:
            # repo make_mesh({"model": m}, ...) OR
            # jax.make_mesh(shape, axis_names)
            first = expr.args[0]
            if isinstance(first, ast.Dict):
                cand = first
            elif len(expr.args) >= 2:
                cand = expr.args[1]
    if cand is None:
        return None
    if isinstance(cand, ast.Dict):
        keys = [
            k.value
            for k in cand.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        ]
        return frozenset(keys) if len(keys) == len(cand.keys) else None
    if isinstance(cand, (ast.Tuple, ast.List)):
        out = []
        for e in cand.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return None
        return frozenset(out)
    if isinstance(cand, ast.Constant) and isinstance(cand.value, str):
        return frozenset([cand.value])
    return None


def _resolve_mesh_axes(
    fi: FuncInfo | None, mesh_expr: ast.AST | None
) -> frozenset[str] | None:
    """Axis names of the mesh operand, when statically known: either
    a literal construction at the call site, or a Name assigned from
    one inside the same function body."""
    if mesh_expr is None:
        return None
    axes = _literal_axes(mesh_expr)
    if axes is not None:
        return axes
    if not isinstance(mesh_expr, ast.Name) or fi is None:
        return None
    found: frozenset[str] | None = None
    for node in ast.walk(fi.node):
        if isinstance(node, _FUNC_NODES) and node is not fi.node:
            continue
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Name)
                and tgt.id == mesh_expr.id
            ):
                found = _literal_axes(node.value)
    return found


# -- psum mirror (check 4) --------------------------------------------


def _count_calls_pathmax(
    stmts: list[ast.stmt], attr: str
) -> int:
    """Max number of `attr`-named calls along any single execution
    path through `stmts`. Exclusive if/else arms take the max arm; an
    arm ending in return/raise/break/continue does not flow into the
    statements after the If. Loops count their body once."""

    def terminates(body: list[ast.stmt]) -> bool:
        return bool(body) and isinstance(
            body[-1],
            (ast.Return, ast.Raise, ast.Break, ast.Continue),
        )

    def calls_in(node: ast.AST) -> int:
        # shallow walk: nested def/lambda bodies are their own units
        n = (
            1
            if isinstance(node, ast.Call) and _callee_name(node) == attr
            else 0
        )
        stack = list(ast.iter_child_nodes(node))
        while stack:
            sub = stack.pop()
            if isinstance(sub, _FUNC_NODES):
                continue
            if (
                isinstance(sub, ast.Call)
                and _callee_name(sub) == attr
            ):
                n += 1
            stack.extend(ast.iter_child_nodes(sub))
        return n

    def block(body: list[ast.stmt]) -> int:
        if not body:
            return 0
        head, rest = body[0], body[1:]
        if isinstance(head, (*_FUNC_NODES, ast.ClassDef)):
            return block(rest)
        if isinstance(head, ast.If):
            r = block(rest)
            v_then = block(head.body) + (
                0 if terminates(head.body) else r
            )
            v_else = block(head.orelse) + (
                0 if terminates(head.orelse) else r
            )
            return calls_in(head.test) + max(v_then, v_else)
        if isinstance(head, (ast.For, ast.AsyncFor, ast.While)):
            return (
                calls_in(
                    head.iter
                    if isinstance(head, (ast.For, ast.AsyncFor))
                    else head.test
                )
                + block(head.body)
                + block(head.orelse)
                + block(rest)
            )
        if isinstance(head, (ast.With, ast.AsyncWith)):
            n = sum(calls_in(i.context_expr) for i in head.items)
            return n + block(head.body) + block(rest)
        if isinstance(head, ast.Try):
            n = block(head.body) + max(
                [0] + [block(h.body) for h in head.handlers]
            )
            return (
                n
                + block(head.orelse)
                + block(head.finalbody)
                + block(rest)
            )
        if isinstance(head, ast.Return):
            return calls_in(head)
        return calls_in(head) + block(rest)

    return block(stmts)


def _pathmax_for_name(ctx: Context, name: str, attr: str) -> int | None:
    """Branch-collapsed `attr`-call count for the function(s) named
    `name` in the corpus (max across same-named candidates); None when
    the name is absent."""
    cands = ctx.graph.by_name.get(name, [])
    if not cands:
        return None
    return max(
        _count_calls_pathmax(list(c.node.body), attr) for c in cands
    )


def _mirror_terms(expr: ast.AST) -> tuple[int, int] | None:
    """(A, B) of a mirror expression `A * <...num_layers...> + B`
    (either operand order; IfExp takes the then-arm)."""
    if isinstance(expr, ast.IfExp):
        expr = expr.body
    if not (
        isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add)
    ):
        return None
    const: int | None = None
    mult: ast.BinOp | None = None
    for side in (expr.left, expr.right):
        if isinstance(side, ast.Constant) and isinstance(
            side.value, int
        ):
            const = side.value
        elif isinstance(side, ast.BinOp) and isinstance(
            side.op, ast.Mult
        ):
            mult = side
    if const is None or mult is None:
        return None
    for side in (mult.left, mult.right):
        if isinstance(side, ast.Constant) and isinstance(
            side.value, int
        ):
            return side.value, const
    return None


def _check_psum_mirror(ctx: Context) -> list[Finding]:
    mirror: tuple[str, ast.Assign] | None = None
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and tgt.attr == MIRROR_ATTR
                ):
                    mirror = (mod.path, node)
    if mirror is None:
        return []
    per_layer_actual = 0
    seen_any = False
    for name in PER_LAYER_FUNCS:
        n = _pathmax_for_name(ctx, name, "psum")
        if n is not None:
            seen_any = True
            per_layer_actual += n
    if not seen_any:
        return []  # partial corpus (mirror without the model): skip
    const_actual = (
        (_pathmax_for_name(ctx, CONST_PSUM_FUNC, "psum") or 0)
        + (_pathmax_for_name(ctx, CONST_GATHER_FUNC, "all_gather") or 0)
    )
    path, node = mirror
    terms = _mirror_terms(node.value)
    if terms is None:
        return [
            Finding(
                "shard-spec",
                path,
                node.lineno,
                node.col_offset,
                f"`{MIRROR_ATTR}` mirror is not of the checkable "
                "form `A * num_layers + B` — keep the "
                "defer_tp_psum_total mirror a statically auditable "
                "affine formula",
            )
        ]
    a, b = terms
    out: list[Finding] = []
    if a != per_layer_actual:
        out.append(
            Finding(
                "shard-spec",
                path,
                node.lineno,
                node.col_offset,
                f"`{MIRROR_ATTR}` claims {a} collectives per layer "
                f"but {'/'.join(PER_LAYER_FUNCS)} contain "
                f"{per_layer_actual} branch-collapsed psum site(s) — "
                "the defer_tp_psum_total mirror drifted from the "
                "sharded forward",
            )
        )
    if b != const_actual:
        out.append(
            Finding(
                "shard-spec",
                path,
                node.lineno,
                node.col_offset,
                f"`{MIRROR_ATTR}` claims {b} per-forward collectives "
                f"outside the layer stack but {CONST_PSUM_FUNC} + "
                f"{CONST_GATHER_FUNC} contain {const_actual} "
                "(psum + all_gather) site(s) — the "
                "defer_tp_psum_total mirror drifted",
            )
        )
    return out


# -- the rule ----------------------------------------------------------


def _shard_map_sites(
    ctx: Context,
) -> Iterator[tuple[FuncInfo | None, ast.Call, str]]:
    seen: set[int] = set()
    for fi in ctx.graph.functions:
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call) and _callee_name(
                node
            ) == "shard_map":
                # ast.walk from an OUTER function also reaches nested
                # defs' bodies; attribute each site to the innermost
                # function so bare-name body resolution scopes right.
                seen.add(id(node))
                yield fi, node, fi.path
    for mod in ctx.modules:
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and _callee_name(node) == "shard_map"
                and id(node) not in seen
            ):
                yield None, node, mod.path


def rule_shard_spec(ctx: Context) -> list[Finding]:
    out: list[Finding] = []
    # innermost-function attribution: map call id -> (fi, call, path)
    sites: dict[int, tuple[FuncInfo | None, ast.Call, str]] = {}
    for fi, call, path in _shard_map_sites(ctx):
        prev = sites.get(id(call))
        if prev is None or (
            fi is not None
            and (prev[0] is None or len(fi.scope) >= len(prev[0].scope))
        ):
            sites[id(call)] = (fi, call, path)
    for fi, call, path in sites.values():
        if not call.args and _kwarg(call, "f") is None:
            continue
        # The compat wrapper's own def-site (forwarding check_rep as a
        # Name) is not a site; only calls are examined here.
        body_expr = call.args[0] if call.args else _kwarg(call, "f")
        mesh_expr = (
            call.args[1] if len(call.args) >= 2 else _kwarg(call, "mesh")
        )
        in_specs = (
            _kwarg(call, "in_specs")
            if _kwarg(call, "in_specs") is not None
            else (call.args[2] if len(call.args) >= 3 else None)
        )
        out_specs = (
            _kwarg(call, "out_specs")
            if _kwarg(call, "out_specs") is not None
            else (call.args[3] if len(call.args) >= 4 else None)
        )

        # 1. arity
        body = _resolve_body(ctx, fi, body_expr)
        if body is not None and in_specs is not None:
            arity = _positional_arity(body)
            specs_len = _spec_tuple_len(in_specs)
            if (
                arity is not None
                and specs_len is not None
                and arity != specs_len
            ):
                bname = (
                    body_expr.id
                    if isinstance(body_expr, ast.Name)
                    else "<lambda>"
                )
                out.append(
                    Finding(
                        "shard-spec",
                        path,
                        call.lineno,
                        call.col_offset,
                        f"shard_map in_specs has {specs_len} "
                        f"entr{'y' if specs_len == 1 else 'ies'} but "
                        f"body `{bname}` takes {arity} positional "
                        "parameter(s) — every operand needs exactly "
                        "one spec",
                    )
                )

        # 2. axis names
        mesh_axes = _resolve_mesh_axes(fi, mesh_expr)
        if mesh_axes is not None:
            for specs in (in_specs, out_specs):
                if specs is None:
                    continue
                for axis, p_call in _axis_names_used(specs):
                    if axis not in mesh_axes:
                        out.append(
                            Finding(
                                "shard-spec",
                                path,
                                p_call.lineno,
                                p_call.col_offset,
                                f"PartitionSpec names axis {axis!r} "
                                "but the mesh only has "
                                f"{sorted(mesh_axes)} — specs must "
                                "name mesh axes",
                            )
                        )

        # 3. check_rep=False demands a justified ignore
        for kw in call.keywords:
            if kw.arg in ("check_rep", "check_vma") and (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is False
            ):
                out.append(
                    Finding(
                        "shard-spec",
                        path,
                        kw.value.lineno,
                        kw.value.col_offset,
                        f"{kw.arg}=False disables shard_map's "
                        "replication checker — say why (a trailing "
                        "`# analysis: ignore[shard-spec] reason`, "
                        "e.g. the body ends in a tiled all_gather "
                        "the checker cannot infer)",
                    )
                )

    out.extend(_check_psum_mirror(ctx))
    return out


RULES["shard-spec"] = rule_shard_spec
