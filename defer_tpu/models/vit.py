"""Vision Transformer (ViT) family — beyond-reference model zoo entry.

The reference's zoo is CNN-only (`tf.keras.applications`, reference
src/test.py:23); ViT is the natural TPU-era counterpart: its compute is
almost entirely MXU-friendly matmuls, and its encoder blocks are the
same uniform stages the pipeline partitioner and the SPMD ppermute
schedule both want. Pre-LN ViT (Dosovitskiy et al., arXiv 2010.11929):

    patch-embed conv (p x p, stride p) -> tokens -> [class] token ->
    learned pos embedding -> L x (LN, MHA, add, LN, MLP, add) ->
    final LN -> [class] head

Cut candidates are the per-block residual outputs (`block_{i}_out`),
so DEFER-style cut lists, `partition_layers="auto"`, and
`run_defer(..., replicas=N)` all apply unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model
from defer_tpu.parallel.spmd_pipeline import (
    make_spmd_pipeline,
    stack_for_stages,
    staged_specs,
)
from defer_tpu.parallel.transformer_stack import (
    TransformerConfig,
    _layer_norm,
    init_stack,
    layers_apply,
    stack_specs,
)


def _build_vit(
    name: str,
    *,
    image_size: int,
    patch_size: int,
    num_layers: int,
    dim: int,
    num_heads: int,
    mlp_dim: int,
    num_classes: int = 1000,
) -> Model:
    if image_size % patch_size:
        raise ValueError(
            f"image size {image_size} not divisible by patch {patch_size}"
        )
    grid = image_size // patch_size
    num_tokens = grid * grid + 1  # + [class]

    b = GraphBuilder(name)
    x = b.input()
    x = b.add(
        "conv",
        x,
        name="patch_embed",
        features=dim,
        kernel_size=(patch_size, patch_size),
        strides=(patch_size, patch_size),
        padding="VALID",
    )
    x = b.add("reshape", x, name="tokens", shape=(grid * grid, dim))
    x = b.add("cls_token", x, name="class_token")
    x = b.add(
        "pos_embedding", x, name="position_embedding", max_len=num_tokens
    )
    cuts: list[str] = []
    for i in range(num_layers):
        h = b.add("layer_norm", x, name=f"block_{i}_ln1")
        h = b.add("mha", h, name=f"block_{i}_mha", num_heads=num_heads)
        x = b.add("add", x, h, name=f"block_{i}_attn_out")
        h = b.add("layer_norm", x, name=f"block_{i}_ln2")
        h = b.add("dense", h, name=f"block_{i}_mlp_in", features=mlp_dim)
        h = b.add("gelu", h, name=f"block_{i}_mlp_gelu")
        h = b.add("dense", h, name=f"block_{i}_mlp_out", features=dim)
        x = b.add("add", x, h, name=f"block_{i}_out")
        cuts.append(x)
    x = b.add("layer_norm", x, name="final_ln")
    x = b.add("take_token", x, name="class_out", index=0)
    x = b.add("dense", x, name="head", features=num_classes)
    return Model(
        name=name,
        graph=b.build(x),
        input_shape=(image_size, image_size, 3),
        cut_candidates=tuple(cuts[:-1]),  # last block output == tail
    )


@register_model("vit_b16")
def vit_b16(image_size: int = 224) -> Model:
    """ViT-Base/16 (86M params)."""
    return _build_vit(
        "vit_b16",
        image_size=image_size,
        patch_size=16,
        num_layers=12,
        dim=768,
        num_heads=12,
        mlp_dim=3072,
    )


@register_model("vit_s16")
def vit_s16(image_size: int = 224) -> Model:
    """ViT-Small/16 (22M params)."""
    return _build_vit(
        "vit_s16",
        image_size=image_size,
        patch_size=16,
        num_layers=12,
        dim=384,
        num_heads=6,
        mlp_dim=1536,
    )


# --------------------------------------------------------------------------
# SPMD form
# --------------------------------------------------------------------------


@dataclasses.dataclass
class SpmdVit:
    """ViT on the shard_map circular pipeline (pre-LN stack).

    Mesh axes (any may be size 1): "data" (batch), "stage" (pipeline),
    "model" (tensor parallel). One jitted step runs patch-embed ->
    S-stage ppermute pipeline -> final LN -> [class] head. The CNN-era
    analogue is impossible in the reference (whole Keras models shipped
    to CPU nodes); this is the TPU-native formulation of the same
    "split a vision model over devices" capability.
    """

    mesh: Mesh
    cfg: TransformerConfig
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    compute_dtype: Any = jnp.bfloat16
    # FSDP: shard stack weights over "data" and all-gather just in
    # time per block — same contract as SpmdBert(fsdp=True).
    fsdp: bool = False

    def __post_init__(self):
        if "stage" not in self.mesh.axis_names:
            raise ValueError("SpmdVit needs a 'stage' mesh axis")
        if self.cfg.norm_style != "pre":
            raise ValueError("ViT uses pre-LN: cfg.norm_style must be 'pre'")
        if self.image_size % self.patch_size:
            raise ValueError(
                f"image {self.image_size} not divisible by patch "
                f"{self.patch_size}"
            )
        self.num_stages = self.mesh.shape.get("stage", 1)
        self.tp_axis = (
            "model" if self.mesh.shape.get("model", 1) > 1 else None
        )
        if self.cfg.num_layers % self.num_stages:
            raise ValueError(
                f"{self.cfg.num_layers} layers not divisible by "
                f"{self.num_stages} stages"
            )
        self.grid = self.image_size // self.patch_size
        self.num_tokens = self.grid * self.grid + 1
        self._fsdp_plan: dict = {}
        if self.fsdp:
            from defer_tpu.parallel.transformer_stack import build_fsdp_plan

            self._fsdp_plan = build_fsdp_plan(
                self.cfg, self._per_layer_specs(), self.mesh
            )

    def _per_layer_specs(self):
        return stack_specs(None, self.tp_axis, cfg=self.cfg)

    def _stack_param_specs(self):
        per_layer = self._per_layer_specs()
        if self._fsdp_plan:
            from defer_tpu.parallel.transformer_stack import fsdp_specs

            per_layer = fsdp_specs(per_layer, self._fsdp_plan, "data")
        return staged_specs(per_layer, "stage")

    def init(self, rng: jax.Array) -> dict:
        from jax.sharding import NamedSharding

        cfg = self.cfg
        kp, ks, kc, kpos, kh = jax.random.split(rng, 5)
        stacked = jax.device_put(
            stack_for_stages(init_stack(ks, cfg), self.num_stages),
            jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s),
                self._stack_param_specs(),
                is_leaf=lambda s: isinstance(s, P),
            ),
        )
        rep = NamedSharding(self.mesh, P())
        pp, d = self.patch_size, cfg.dim
        scale = (pp * pp * 3) ** -0.5
        return {
            "patch_kernel": jax.device_put(
                jax.random.normal(kp, (pp, pp, 3, d)) * scale, rep
            ),
            "patch_bias": jax.device_put(jnp.zeros((d,)), rep),
            "cls": jax.device_put(
                jax.random.normal(kc, (1, 1, d)) * 0.02, rep
            ),
            "pos": jax.device_put(
                jax.random.normal(kpos, (self.num_tokens, d)) * 0.02, rep
            ),
            "final_ln_scale": jax.device_put(jnp.ones((d,)), rep),
            "final_ln_bias": jax.device_put(jnp.zeros((d,)), rep),
            "head_w": jax.device_put(
                jax.random.normal(kh, (d, self.num_classes)) * d**-0.5,
                rep,
            ),
            "head_b": jax.device_put(jnp.zeros((self.num_classes,)), rep),
            "stack": stacked,
        }

    def _embed(self, params: dict, images: jax.Array) -> jax.Array:
        """[N, H, W, 3] -> [N, tokens, D] (patch conv + cls + pos)."""
        cd = self.compute_dtype
        x = lax.conv_general_dilated(
            images.astype(cd),
            params["patch_kernel"].astype(cd),
            window_strides=(self.patch_size, self.patch_size),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + params["patch_bias"].astype(cd)
        n = x.shape[0]
        x = x.reshape(n, self.grid * self.grid, self.cfg.dim)
        cls = jnp.broadcast_to(
            params["cls"].astype(cd), (n, 1, self.cfg.dim)
        )
        x = jnp.concatenate([cls, x], axis=1)
        return x + params["pos"].astype(cd)

    def make_step(self):
        """Jitted (params, images [M, B, H, W, 3]) -> logits [M, B, C].
        Memoized (defer_tpu/utils/memo.py)."""
        from defer_tpu.utils.memo import cached_step

        return cached_step(self, "step", self._build_step)

    def _build_step(self):
        cfg = self.cfg

        def stage_fn(stack_local, x):
            return layers_apply(
                stack_local,
                x,
                cfg,
                tp_axis=self.tp_axis,
                fsdp_axis="data" if self._fsdp_plan else None,
                fsdp_gather=self._fsdp_plan,
            )

        pipe = make_spmd_pipeline(
            self.mesh,
            stage_fn,
            self._stack_param_specs(),
            stage_axis="stage",
            data_axis="data" if self.mesh.shape.get("data", 1) > 1 else None,
        )

        def step(params, images):
            m, b = images.shape[:2]
            emb = self._embed(
                params, images.reshape(m * b, *images.shape[2:])
            ).reshape(m, b, self.num_tokens, cfg.dim)
            ys = pipe(params["stack"], emb)
            return self._head(params, ys)

        return jax.jit(step)

    def _head(self, params: dict, ys: jax.Array) -> jax.Array:
        """Final LN on the [class] token + classifier head — ONE
        definition shared by the pipelined step and the correctness
        reference."""
        cd = self.compute_dtype
        cls = _layer_norm(
            ys[:, :, 0, :].astype(cd),
            params["final_ln_scale"],
            params["final_ln_bias"],
            self.cfg.layer_norm_eps,
        )
        return cls @ params["head_w"].astype(cd) + params["head_b"].astype(cd)

    def reference_apply(self, params: dict, images: jax.Array) -> jax.Array:
        """Unpipelined single-program reference for correctness checks."""
        cfg = self.cfg
        m, b = images.shape[:2]
        emb = self._embed(
            params, images.reshape(m * b, *images.shape[2:])
        ).reshape(m, b, self.num_tokens, cfg.dim)
        flat = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a).reshape(-1, *a.shape[2:]),
            params["stack"],
        )
        ys = jnp.stack([layers_apply(flat, emb[i], cfg) for i in range(m)])
        return self._head(params, ys)


@register_model("vit_tiny")
def vit_tiny(image_size: int = 32) -> Model:
    """Small config for tests / CPU meshes."""
    return _build_vit(
        "vit_tiny",
        image_size=image_size,
        patch_size=8,
        num_layers=4,
        dim=64,
        num_heads=4,
        mlp_dim=128,
        num_classes=10,
    )
