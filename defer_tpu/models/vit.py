"""Vision Transformer (ViT) family — beyond-reference model zoo entry.

The reference's zoo is CNN-only (`tf.keras.applications`, reference
src/test.py:23); ViT is the natural TPU-era counterpart: its compute is
almost entirely MXU-friendly matmuls, and its encoder blocks are the
same uniform stages the pipeline partitioner and the SPMD ppermute
schedule both want. Pre-LN ViT (Dosovitskiy et al., arXiv 2010.11929):

    patch-embed conv (p x p, stride p) -> tokens -> [class] token ->
    learned pos embedding -> L x (LN, MHA, add, LN, MLP, add) ->
    final LN -> [class] head

Cut candidates are the per-block residual outputs (`block_{i}_out`),
so DEFER-style cut lists, `partition_layers="auto"`, and
`run_defer(..., replicas=N)` all apply unchanged.
"""

from __future__ import annotations

import jax.numpy as jnp

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model


def _build_vit(
    name: str,
    *,
    image_size: int,
    patch_size: int,
    num_layers: int,
    dim: int,
    num_heads: int,
    mlp_dim: int,
    num_classes: int = 1000,
) -> Model:
    if image_size % patch_size:
        raise ValueError(
            f"image size {image_size} not divisible by patch {patch_size}"
        )
    grid = image_size // patch_size
    num_tokens = grid * grid + 1  # + [class]

    b = GraphBuilder(name)
    x = b.input()
    x = b.add(
        "conv",
        x,
        name="patch_embed",
        features=dim,
        kernel_size=(patch_size, patch_size),
        strides=(patch_size, patch_size),
        padding="VALID",
    )
    x = b.add("reshape", x, name="tokens", shape=(grid * grid, dim))
    x = b.add("cls_token", x, name="class_token")
    x = b.add(
        "pos_embedding", x, name="position_embedding", max_len=num_tokens
    )
    cuts: list[str] = []
    for i in range(num_layers):
        h = b.add("layer_norm", x, name=f"block_{i}_ln1")
        h = b.add("mha", h, name=f"block_{i}_mha", num_heads=num_heads)
        x = b.add("add", x, h, name=f"block_{i}_attn_out")
        h = b.add("layer_norm", x, name=f"block_{i}_ln2")
        h = b.add("dense", h, name=f"block_{i}_mlp_in", features=mlp_dim)
        h = b.add("gelu", h, name=f"block_{i}_mlp_gelu")
        h = b.add("dense", h, name=f"block_{i}_mlp_out", features=dim)
        x = b.add("add", x, h, name=f"block_{i}_out")
        cuts.append(x)
    x = b.add("layer_norm", x, name="final_ln")
    x = b.add("take_token", x, name="class_out", index=0)
    x = b.add("dense", x, name="head", features=num_classes)
    return Model(
        name=name,
        graph=b.build(x),
        input_shape=(image_size, image_size, 3),
        cut_candidates=tuple(cuts[:-1]),  # last block output == tail
    )


@register_model("vit_b16")
def vit_b16(image_size: int = 224) -> Model:
    """ViT-Base/16 (86M params)."""
    return _build_vit(
        "vit_b16",
        image_size=image_size,
        patch_size=16,
        num_layers=12,
        dim=768,
        num_heads=12,
        mlp_dim=3072,
    )


@register_model("vit_s16")
def vit_s16(image_size: int = 224) -> Model:
    """ViT-Small/16 (22M params)."""
    return _build_vit(
        "vit_s16",
        image_size=image_size,
        patch_size=16,
        num_layers=12,
        dim=384,
        num_heads=6,
        mlp_dim=1536,
    )


@register_model("vit_tiny")
def vit_tiny(image_size: int = 32) -> Model:
    """Small config for tests / CPU meshes."""
    return _build_vit(
        "vit_tiny",
        image_size=image_size,
        patch_size=8,
        num_layers=4,
        dim=64,
        num_heads=4,
        mlp_dim=128,
        num_classes=10,
    )
