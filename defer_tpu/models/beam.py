"""Beam-search decoding on the KV-cache decoder.

Greedy decoding commits to the locally best token; beam search keeps
the `beam_size` best partial sequences. TPU-shaped on the existing
decoder: beams ARE the batch (one compiled (beam, 1) step), and a
beam reorder is a GATHER along the cache's batch axis — static
shapes, no host-side cache surgery. Scores are summed log-probs with
an optional length penalty.

Part of the beyond-reference serving surface (the reference streams
CNN frames, src/test.py:30-41); composes with the same decoders as
generate/speculative/continuous batching (flat or rolling caches,
any family).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def beam_search(
    dec: Any,
    params: dict,
    prompt_ids: jax.Array,
    num_steps: int,
    *,
    beam_size: int = 4,
    length_penalty: float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    """Beam-search continuation of `prompt_ids` [1, T0].

    Returns (ids [beam, T0 + num_steps], scores [beam]) sorted best
    first; scores are sum log-prob / (length ** length_penalty).
    beam_size=1 reduces exactly to greedy `generate`.

    With fixed-length decoding (no EOS; every beam generates exactly
    num_steps tokens) length_penalty only RESCALES scores — it cannot
    reorder beams until variable-length termination exists."""
    if prompt_ids.shape[0] != 1:
        raise ValueError("beam_search takes one prompt ([1, T0])")
    if beam_size < 1:
        raise ValueError(f"beam_size={beam_size} must be >= 1")
    t0 = prompt_ids.shape[1]
    if not getattr(dec, "rolling_cache", False) and (
        t0 + num_steps > dec.cfg.max_len
    ):
        raise ValueError(
            f"prompt {t0} + steps {num_steps} exceeds max_len "
            f"{dec.cfg.max_len}"
        )

    B = beam_size
    step = dec.make_step(donate=False)
    # Prefill ONCE at batch 1 (prefill owns chunking for rolling
    # caches and long prompts), then broadcast the cache lanes: the
    # beams' prompt states are byte-identical, so computing them
    # beam_size times would be pure waste.
    small = dec.init_cache(1)
    last, small = dec.prefill(params, small, prompt_ids)
    cache = {
        "k": jnp.repeat(small["k"], B, axis=1),
        "v": jnp.repeat(small["v"], B, axis=1),
        "pos": small["pos"],
    }
    ids = jnp.tile(prompt_ids, (B, 1))
    logp = jax.nn.log_softmax(last.astype(jnp.float32), -1)  # (1, V)
    # All beams start identical: only beam 0 may seed candidates, or
    # the first expansion would pick the same token B times.
    scores = jnp.where(jnp.arange(B) == 0, 0.0, -jnp.inf)

    vocab = logp.shape[-1]
    for i in range(num_steps):
        total = scores[:, None] + logp  # (B, V) by broadcast
        scores, flat = jax.lax.top_k(total.reshape(-1), B)
        beam_idx = flat // vocab
        token = (flat % vocab).astype(ids.dtype)
        ids = jnp.concatenate(
            [ids[beam_idx], token[:, None]], axis=1
        )
        if i + 1 == num_steps:
            # The final tokens' successor logits are never used.
            break
        # Reorder beam lanes: gather along the cache batch axis.
        cache = {
            "k": cache["k"][:, beam_idx],
            "v": cache["v"][:, beam_idx],
            "pos": cache["pos"],
        }
        logits, cache = step(params, cache, token[:, None])
        logp = jax.nn.log_softmax(
            logits[:, -1, :].astype(jnp.float32), -1
        )

    if length_penalty:
        scores = scores / (num_steps**length_penalty)
    order = jnp.argsort(-scores)
    return ids[order], scores[order]
