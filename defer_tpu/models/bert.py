"""bert — implemented in a later milestone this round."""
