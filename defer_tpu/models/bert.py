"""BERT-base encoder (BASELINE.json config: "BERT-base encoder inference
(Keras-NLP, transformer stages)").

Two forms:

  * IR graph (`bert_base`) — token-id input, embeddings, 12 encoder
    blocks, CLS pooler. Cut candidates are the block outputs
    (`encoder_{i}_out`), so the DEFER-style heterogeneous pipeline cuts
    it at block boundaries exactly as the reference would have cut a
    Keras BERT.
  * SPMD form (`SpmdBert`) — the TPU-first path: stacked encoder blocks
    on the shard_map circular pipeline (defer_tpu/parallel), composing
    pipeline/data/tensor mesh axes in one jitted program.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model
from defer_tpu.parallel.spmd_pipeline import (
    make_spmd_pipeline,
    stack_for_stages,
    staged_specs,
)
from defer_tpu.parallel.transformer_stack import (
    TransformerConfig,
    init_stack,
    layers_apply,
    stack_specs,
)


def _build_bert(
    name: str,
    *,
    num_layers: int,
    dim: int,
    num_heads: int,
    ffn_dim: int,
    vocab_size: int,
    max_len: int,
    seq_len: int,
) -> Model:
    b = GraphBuilder(name)
    ids = b.input("input_ids")
    x = b.add(
        "embedding",
        ids,
        name="token_embedding",
        vocab_size=vocab_size,
        features=dim,
    )
    x = b.add("pos_embedding", x, name="position_embedding", max_len=max_len)
    x = b.add("layer_norm", x, name="embeddings_ln")
    cuts: list[str] = []
    for i in range(num_layers):
        attn = b.add("mha", x, name=f"encoder_{i}_mha", num_heads=num_heads)
        x = b.add("add", x, attn, name=f"encoder_{i}_attn_add")
        x = b.add("layer_norm", x, name=f"encoder_{i}_attn_ln")
        h = b.add("dense", x, name=f"encoder_{i}_ffn_in", features=ffn_dim)
        h = b.add("gelu", h, name=f"encoder_{i}_ffn_gelu")
        h = b.add("dense", h, name=f"encoder_{i}_ffn_out", features=dim)
        x = b.add("add", x, h, name=f"encoder_{i}_ffn_add")
        x = b.add("layer_norm", x, name=f"encoder_{i}_out")
        cuts.append(x)
    cls = b.add("take_token", x, name="cls_token", index=0)
    pooled = b.add("dense", cls, name="pooler_dense", features=dim)
    pooled = b.add("tanh", pooled, name="pooler")
    return Model(
        name=name,
        graph=b.build(pooled),
        input_shape=(seq_len,),
        input_dtype=jnp.int32,
        cut_candidates=tuple(cuts[:-1]),  # last block output == graph tail
    )


@register_model("bert_base")
def bert_base(seq_len: int = 128) -> Model:
    return _build_bert(
        "bert_base",
        num_layers=12,
        dim=768,
        num_heads=12,
        ffn_dim=3072,
        vocab_size=30522,
        max_len=512,
        seq_len=seq_len,
    )


@register_model("bert_tiny")
def bert_tiny(seq_len: int = 16) -> Model:
    """Small config for tests / CPU meshes."""
    return _build_bert(
        "bert_tiny",
        num_layers=4,
        dim=32,
        num_heads=4,
        ffn_dim=64,
        vocab_size=128,
        max_len=64,
        seq_len=seq_len,
    )


# --------------------------------------------------------------------------
# SPMD form
# --------------------------------------------------------------------------


@dataclasses.dataclass
class SpmdBert:
    """BERT encoder on the shard_map circular pipeline.

    Mesh axes (any may be size 1): "data" (batch), "stage" (pipeline),
    "model" (tensor parallel). One jitted step runs
    embed -> S-stage ppermute pipeline -> pooler.
    """

    mesh: Mesh
    cfg: TransformerConfig
    compute_dtype: Any = jnp.bfloat16
    sp_strategy: str = "ring"
    # FSDP: additionally shard each stack weight over the "data" axis
    # (at-rest memory 1/dp per chip) and all-gather it just in time in
    # the block body (transformer_stack.layers_apply) — the gather's
    # transpose is the reduce-scatter sharded gradients need.
    fsdp: bool = False

    def __post_init__(self):
        if "stage" not in self.mesh.axis_names:
            raise ValueError(
                "SpmdBert needs a 'stage' mesh axis (size 1 is fine): "
                f"got axes {self.mesh.axis_names}"
            )
        self.num_stages = self.mesh.shape.get("stage", 1)
        self.tp_axis = "model" if self.mesh.shape.get("model", 1) > 1 else None
        self.sp_axis = "seq" if self.mesh.shape.get("seq", 1) > 1 else None
        ep = self.mesh.shape.get("expert", 1)
        self.ep_axis = "expert" if ep > 1 else None
        if self.cfg.num_experts and self.cfg.num_experts % ep:
            raise ValueError(
                f"{self.cfg.num_experts} experts not divisible by the "
                f"expert axis size {ep}"
            )
        if ep > 1 and not self.cfg.num_experts:
            raise ValueError(
                "mesh has an expert axis but cfg.num_experts == 0"
            )
        if self.cfg.num_layers % self.num_stages:
            raise ValueError(
                f"{self.cfg.num_layers} layers not divisible by "
                f"{self.num_stages} pipeline stages"
            )
        tp = self.mesh.shape.get("model", 1)
        if self.cfg.num_heads % tp or self.cfg.dim % tp or self.cfg.ffn_dim % tp:
            raise ValueError(
                f"heads={self.cfg.num_heads}, dim={self.cfg.dim}, "
                f"ffn_dim={self.cfg.ffn_dim} must all divide by the model "
                f"axis size {tp} — otherwise attention silently computes "
                "with the wrong head grouping"
            )
        if self.cfg.kv_heads % tp:
            raise ValueError(
                f"num_kv_heads={self.cfg.kv_heads} must divide by the "
                f"model axis size {tp} (whole kv head groups per shard)"
            )
        self._fsdp_plan: dict = {}
        if self.fsdp:
            from defer_tpu.parallel.transformer_stack import build_fsdp_plan

            self._fsdp_plan = build_fsdp_plan(
                self.cfg, self._per_layer_specs(), self.mesh
            )

    def _per_layer_specs(self):
        return stack_specs(
            None,
            self.tp_axis,
            ep_axis=self.ep_axis,
            moe=bool(self.cfg.num_experts),
            cfg=self.cfg,
        )

    def _stack_param_specs(self):
        per_layer = self._per_layer_specs()
        if self._fsdp_plan:
            from defer_tpu.parallel.transformer_stack import fsdp_specs

            per_layer = fsdp_specs(per_layer, self._fsdp_plan, "data")
        return staged_specs(per_layer, "stage")

    def _stack_shardings(self):
        from jax.sharding import NamedSharding

        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s),
            self._stack_param_specs(),
            is_leaf=lambda s: isinstance(s, P),
        )

    def init(self, rng: jax.Array) -> dict:
        k_embed, k_stack, k_pool = jax.random.split(rng, 3)
        cfg = self.cfg
        stacked = jax.device_put(
            stack_for_stages(init_stack(k_stack, cfg), self.num_stages),
            self._stack_shardings(),
        )
        from jax.sharding import NamedSharding

        rep = NamedSharding(self.mesh, P())
        params = {
            "token_embedding": jax.device_put(
                jax.random.normal(k_embed, (cfg.vocab_size, cfg.dim)) * 0.02,
                rep,
            ),
            "pooler_w": jax.device_put(
                jax.random.normal(k_pool, (cfg.dim, cfg.dim)) * cfg.dim**-0.5,
                rep,
            ),
            "pooler_b": jax.device_put(jnp.zeros((cfg.dim,)), rep),
            "stack": stacked,
        }
        if cfg.pos_style == "learned":
            params["pos_embedding"] = jax.device_put(
                jax.random.normal(
                    jax.random.fold_in(k_embed, 1), (cfg.max_len, cfg.dim)
                )
                * 0.02,
                rep,
            )
        return params

    def make_step(self):
        """Jitted (params, ids [M, B, S]) -> pooled [M, B, D].
        Memoized (defer_tpu/utils/memo.py)."""
        from defer_tpu.utils.memo import cached_step

        return cached_step(self, "step", self._build_step)

    def _embed_and_pipe(self):
        """The shared forward core: token (+learned position) embed ->
        pipelined stack, (params, ids [M, B, S]) -> [M, B, S, D].
        Both public steps (pooled and hidden) are tails on this ONE
        construction, so the stage wiring cannot drift between them."""
        cfg = self.cfg
        cd = self.compute_dtype

        def stage_fn(stack_local, x):
            return layers_apply(
                stack_local,
                x,
                cfg,
                tp_axis=self.tp_axis,
                sp_axis=self.sp_axis,
                sp_strategy=self.sp_strategy,
                ep_axis=self.ep_axis,
                fsdp_axis="data" if self._fsdp_plan else None,
                fsdp_gather=self._fsdp_plan,
            )

        pipe = make_spmd_pipeline(
            self.mesh,
            stage_fn,
            self._stack_param_specs(),
            stage_axis="stage",
            data_axis="data" if self.mesh.shape.get("data", 1) > 1 else None,
            seq_axis=self.sp_axis,
        )

        def hidden(params, ids):
            seq = ids.shape[-1]
            emb = jnp.take(params["token_embedding"], ids, axis=0)
            if cfg.pos_style == "learned":
                emb = emb + params["pos_embedding"][:seq]
            return pipe(params["stack"], emb.astype(cd))

        return hidden

    def _build_step(self):
        cd = self.compute_dtype
        hidden = self._embed_and_pipe()

        def step(params, ids):
            ys = hidden(params, ids)  # [M, B, S, D]
            cls = ys[:, :, 0, :]
            return jnp.tanh(
                cls @ params["pooler_w"].astype(cd)
                + params["pooler_b"].astype(cd)
            )

        return jax.jit(step)

    def make_hidden_step(self):
        """Jitted (params, ids [M, B, S]) -> per-position hidden states
        [M, B, S, D] (no pooler) — the forward a next-token LM head
        needs (parallel/train.py::make_lm_train_step). Memoized."""
        from defer_tpu.utils.memo import cached_step

        return cached_step(self, "hidden", self._build_hidden_step)

    def _build_hidden_step(self):
        return jax.jit(self._embed_and_pipe())

    def reference_apply(self, params: dict, ids: jax.Array) -> jax.Array:
        """Unpipelined single-program reference for correctness checks."""
        cfg = self.cfg
        cd = self.compute_dtype
        seq = ids.shape[-1]
        emb = jnp.take(params["token_embedding"], ids, axis=0)
        if cfg.pos_style == "learned":
            emb = emb + params["pos_embedding"][:seq]
        emb = emb.astype(cd)
        # Undo the stage stacking: [S, L/S, ...] -> [L, ...]
        flat = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a).reshape(-1, *a.shape[2:]),
            params["stack"],
        )
        out = jnp.stack(
            [layers_apply(flat, emb[m], cfg) for m in range(emb.shape[0])]
        )
        cls = out[:, :, 0, :]
        return jnp.tanh(
            cls @ params["pooler_w"].astype(cd)
            + params["pooler_b"].astype(cd)
        )
