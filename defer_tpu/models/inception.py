"""InceptionV3 — multi-branch CNN (BASELINE.json: "VGG19 + InceptionV3").

Native IR build with Keras-compatible module naming: the eleven
inception-module concat outputs are named `mixed0` ... `mixed10`, the
points a reference user would cut at. Each `mixedN` concat dominates
everything downstream, so all eleven are valid single-tensor cut points;
the branches *inside* a module are not (SURVEY.md §3.4 — the reference
would silently miscompile such cuts, our partitioner rejects them).

The multi-path branches also exercise the memoized traversal the
reference lacks (reference src/dag_util.py:18-19 re-calls shared layers
once per path; our IR caches each node — defer_tpu/graph/ir.py).
"""

from __future__ import annotations

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model


def _cb(
    b: GraphBuilder,
    x: str,
    features: int,
    kernel,
    *,
    strides: int = 1,
    padding: str = "SAME",
    prefix: str,
) -> str:
    """conv -> BN -> relu, the Inception-family building block (shared
    with inception_resnet.py)."""
    x = b.add(
        "conv",
        x,
        name=f"{prefix}_conv",
        features=features,
        kernel_size=kernel,
        strides=strides,
        padding=padding,
        use_bias=False,
    )
    x = b.add("batch_norm", x, name=f"{prefix}_bn", eps=1e-3)
    return b.add("relu", x, name=f"{prefix}_relu")


def _inception_stem(b: GraphBuilder, x: str) -> str:
    """Shared V3 / InceptionResNetV2 stem: 299x299x3 -> 35x35x192."""
    x = _cb(b, x, 32, 3, strides=2, padding="VALID", prefix="stem1")
    x = _cb(b, x, 32, 3, padding="VALID", prefix="stem2")
    x = _cb(b, x, 64, 3, prefix="stem3")
    x = b.add("max_pool", x, name="stem_pool1", window=3, strides=2, padding="VALID")
    x = _cb(b, x, 80, 1, padding="VALID", prefix="stem4")
    x = _cb(b, x, 192, 3, padding="VALID", prefix="stem5")
    return b.add("max_pool", x, name="stem_pool2", window=3, strides=2, padding="VALID")


def _block_a(b: GraphBuilder, x: str, pool_ch: int, *, name: str) -> str:
    """35x35 module: 1x1 / 5x5 / double-3x3 / avgpool branches."""
    b1 = _cb(b, x, 64, 1, prefix=f"{name}_b1x1")
    b5 = _cb(b, x, 48, 1, prefix=f"{name}_b5x5_1")
    b5 = _cb(b, b5, 64, 5, prefix=f"{name}_b5x5_2")
    b3 = _cb(b, x, 64, 1, prefix=f"{name}_b3x3dbl_1")
    b3 = _cb(b, b3, 96, 3, prefix=f"{name}_b3x3dbl_2")
    b3 = _cb(b, b3, 96, 3, prefix=f"{name}_b3x3dbl_3")
    bp = b.add(
        "avg_pool", x, name=f"{name}_pool", window=3, strides=1, padding="SAME"
    )
    bp = _cb(b, bp, pool_ch, 1, prefix=f"{name}_bpool")
    return b.add("concat", b1, b5, b3, bp, name=name)


def _reduction_a(b: GraphBuilder, x: str, *, name: str) -> str:
    """35x35 -> 17x17: strided 3x3 / strided double-3x3 / maxpool."""
    b3 = _cb(b, x, 384, 3, strides=2, padding="VALID", prefix=f"{name}_b3x3")
    bd = _cb(b, x, 64, 1, prefix=f"{name}_b3x3dbl_1")
    bd = _cb(b, bd, 96, 3, prefix=f"{name}_b3x3dbl_2")
    bd = _cb(b, bd, 96, 3, strides=2, padding="VALID", prefix=f"{name}_b3x3dbl_3")
    bp = b.add(
        "max_pool", x, name=f"{name}_pool", window=3, strides=2, padding="VALID"
    )
    return b.add("concat", b3, bd, bp, name=name)


def _block_b(b: GraphBuilder, x: str, mid: int, *, name: str) -> str:
    """17x17 module with factorized 7x1/1x7 branches."""
    b1 = _cb(b, x, 192, 1, prefix=f"{name}_b1x1")
    b7 = _cb(b, x, mid, 1, prefix=f"{name}_b7x7_1")
    b7 = _cb(b, b7, mid, (1, 7), prefix=f"{name}_b7x7_2")
    b7 = _cb(b, b7, 192, (7, 1), prefix=f"{name}_b7x7_3")
    bd = _cb(b, x, mid, 1, prefix=f"{name}_b7x7dbl_1")
    bd = _cb(b, bd, mid, (7, 1), prefix=f"{name}_b7x7dbl_2")
    bd = _cb(b, bd, mid, (1, 7), prefix=f"{name}_b7x7dbl_3")
    bd = _cb(b, bd, mid, (7, 1), prefix=f"{name}_b7x7dbl_4")
    bd = _cb(b, bd, 192, (1, 7), prefix=f"{name}_b7x7dbl_5")
    bp = b.add(
        "avg_pool", x, name=f"{name}_pool", window=3, strides=1, padding="SAME"
    )
    bp = _cb(b, bp, 192, 1, prefix=f"{name}_bpool")
    return b.add("concat", b1, b7, bd, bp, name=name)


def _reduction_b(b: GraphBuilder, x: str, *, name: str) -> str:
    """17x17 -> 8x8."""
    b3 = _cb(b, x, 192, 1, prefix=f"{name}_b3x3_1")
    b3 = _cb(b, b3, 320, 3, strides=2, padding="VALID", prefix=f"{name}_b3x3_2")
    b7 = _cb(b, x, 192, 1, prefix=f"{name}_b7x7x3_1")
    b7 = _cb(b, b7, 192, (1, 7), prefix=f"{name}_b7x7x3_2")
    b7 = _cb(b, b7, 192, (7, 1), prefix=f"{name}_b7x7x3_3")
    b7 = _cb(b, b7, 192, 3, strides=2, padding="VALID", prefix=f"{name}_b7x7x3_4")
    bp = b.add(
        "max_pool", x, name=f"{name}_pool", window=3, strides=2, padding="VALID"
    )
    return b.add("concat", b3, b7, bp, name=name)


def _block_c(b: GraphBuilder, x: str, *, name: str) -> str:
    """8x8 module with split 1x3/3x1 fan-out branches."""
    b1 = _cb(b, x, 320, 1, prefix=f"{name}_b1x1")
    b3 = _cb(b, x, 384, 1, prefix=f"{name}_b3x3_1")
    b3a = _cb(b, b3, 384, (1, 3), prefix=f"{name}_b3x3_2a")
    b3b = _cb(b, b3, 384, (3, 1), prefix=f"{name}_b3x3_2b")
    b3 = b.add("concat", b3a, b3b, name=f"{name}_b3x3")
    bd = _cb(b, x, 448, 1, prefix=f"{name}_b3x3dbl_1")
    bd = _cb(b, bd, 384, 3, prefix=f"{name}_b3x3dbl_2")
    bda = _cb(b, bd, 384, (1, 3), prefix=f"{name}_b3x3dbl_3a")
    bdb = _cb(b, bd, 384, (3, 1), prefix=f"{name}_b3x3dbl_3b")
    bd = b.add("concat", bda, bdb, name=f"{name}_b3x3dbl")
    bp = b.add(
        "avg_pool", x, name=f"{name}_pool", window=3, strides=1, padding="SAME"
    )
    bp = _cb(b, bp, 192, 1, prefix=f"{name}_bpool")
    return b.add("concat", b1, b3, bd, bp, name=name)


@register_model("inceptionv3")
def inceptionv3(num_classes: int = 1000) -> Model:
    b = GraphBuilder("inceptionv3")
    x = b.input("input")
    x = _inception_stem(b, x)

    x = _block_a(b, x, 32, name="mixed0")
    x = _block_a(b, x, 64, name="mixed1")
    x = _block_a(b, x, 64, name="mixed2")
    x = _reduction_a(b, x, name="mixed3")
    x = _block_b(b, x, 128, name="mixed4")
    x = _block_b(b, x, 160, name="mixed5")
    x = _block_b(b, x, 160, name="mixed6")
    x = _block_b(b, x, 192, name="mixed7")
    x = _reduction_b(b, x, name="mixed8")
    x = _block_c(b, x, name="mixed9")
    x = _block_c(b, x, name="mixed10")

    x = b.add("global_avg_pool", x, name="avg_pool")
    x = b.add("dense", x, name="predictions_dense", features=num_classes)
    x = b.add("softmax", x, name="predictions")
    return Model(
        name="inceptionv3",
        graph=b.build(x),
        input_shape=(299, 299, 3),
        cut_candidates=tuple(f"mixed{i}" for i in range(11)),
    )
