"""Model zoo: the BASELINE.json config list, built on the graph IR.

The reference pulls its zoo from `tf.keras.applications` (only ResNet50
is exercised in-repo, reference src/test.py:23, src/local_infer.py:8).
Here each model is built natively as an IR graph with Keras-compatible
node names, so reference-style cut lists ("add_2", "add_4", ...,
reference src/test.py:27) apply unchanged.

Registry:
    model = get_model("resnet50")        # -> Model(graph, input_shape, ...)
    params = model.init(jax.random.key(0))
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from defer_tpu.graph.ir import Graph, GraphParams


@dataclasses.dataclass(frozen=True)
class Model:
    """A zoo model: IR graph + input spec + recommended cut points.

    `default_cuts(n)` returns n-1 cut points giving n roughly balanced
    stages — the analogue of the documented cut list the reference makes
    the user pick by hand (reference src/test.py:24-28).
    """

    name: str
    graph: Graph
    input_shape: tuple[int, ...]  # without batch dim
    input_dtype: Any = jnp.float32
    # Each candidate is one boundary: a node name, or a tuple of names
    # for a multi-tensor bundle (NASNet's (cell_i, cell_i-1) pairs).
    cut_candidates: tuple[str | tuple[str, ...], ...] = ()
    # IR node name -> layer name in the real tf.keras checkpoint, for
    # transplanting actual Keras artifacts into the native graph (the
    # reference consumes real checkpoints via set_weights, reference
    # src/node.py:38-45). None = identity (names already match).
    keras_name_map: Callable[[str], str] | None = None

    def init(
        self,
        rng: jax.Array,
        *,
        batch_size: int = 1,
        param_dtype: Any = jnp.float32,
        compute_dtype: Any = jnp.float32,
    ) -> GraphParams:
        del compute_dtype  # shape inference only needs the input dtype
        return self.graph.init(
            rng,
            (batch_size, *self.input_shape),
            param_dtype=param_dtype,
            input_dtype=self.input_dtype,
        )

    def example_input(
        self, batch_size: int = 1, dtype: Any | None = None
    ) -> jax.Array:
        dtype = dtype or self.input_dtype
        shape = (batch_size, *self.input_shape)
        if jnp.issubdtype(dtype, jnp.integer):
            return jnp.zeros(shape, dtype)
        return jnp.ones(shape, dtype)

    def default_cuts(self, num_stages: int) -> list[str | tuple[str, ...]]:
        if num_stages < 1:
            raise ValueError("num_stages must be >= 1")
        if num_stages == 1:
            return []
        cands = self.cut_candidates
        if num_stages - 1 > len(cands):
            raise ValueError(
                f"{self.name} has {len(cands)} candidate cut points; "
                f"cannot make {num_stages} stages"
            )
        # Evenly spaced picks, kept strictly increasing so we always
        # return exactly num_stages-1 distinct cuts.
        picks: list[int] = []
        prev = -1
        remaining = num_stages - 1
        for i in range(num_stages - 1):
            j = round((i + 1) * len(cands) / num_stages) - 1
            j = max(j, prev + 1)
            j = min(j, len(cands) - (remaining - i))
            picks.append(j)
            prev = j
        return [cands[j] for j in picks]


_BUILDERS: dict[str, Callable[..., Model]] = {}


def register_model(name: str) -> Callable:
    def deco(fn: Callable[..., Model]) -> Callable[..., Model]:
        _BUILDERS[name] = fn
        return fn

    return deco


def _load_zoo() -> None:
    """Import every zoo module for its register_model side effects."""
    import importlib

    for mod in (
        "bert",
        "densenet",
        "efficientnet",
        "inception",
        "inception_resnet",
        "mobilenet",
        "nasnet",
        "resnet",
        "vgg",
        "vit",
        "xception",
    ):
        importlib.import_module(f"defer_tpu.models.{mod}")


def get_model(name: str, **kwargs: Any) -> Model:
    _load_zoo()
    try:
        builder = _BUILDERS[name]
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; available: {sorted(_BUILDERS)}"
        ) from None
    return builder(**kwargs)


def model_names() -> list[str]:
    _load_zoo()
    return sorted(_BUILDERS)
