"""EfficientNet-B0 — MBConv + squeeze-excite edge model (BASELINE.json:
"MobileNetV2 / EfficientNet-B0 (depthwise-conv edge models)").

Native IR build of the B0 architecture: stem conv, seven MBConv groups
with squeeze-and-excitation, swish activations, 1280-wide head. Block
outputs chain linearly (residual adds stay inside a block; the SE branch
rejoins via `multiply` inside the block), so every block output is a
valid single-tensor cut point (SURVEY.md §3.4).
"""

from __future__ import annotations

import math

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model
from defer_tpu.models.mobilenet import _make_divisible


def _conv_bn_swish(
    b: GraphBuilder,
    x: str,
    features: int,
    kernel: int,
    *,
    strides: int = 1,
    swish: bool = True,
    prefix: str,
) -> str:
    x = b.add(
        "conv",
        x,
        name=f"{prefix}_conv",
        features=features,
        kernel_size=kernel,
        strides=strides,
        padding="SAME",
        use_bias=False,
    )
    x = b.add("batch_norm", x, name=f"{prefix}_bn", eps=1e-3)
    if swish:
        x = b.add("swish", x, name=f"{prefix}_activation")
    return x


def _se_block(
    b: GraphBuilder, x: str, in_ch: int, expanded_ch: int, *, prefix: str
) -> str:
    """Squeeze-and-excitation: GAP -> reduce conv -> swish -> expand conv
    -> sigmoid -> channel-wise gate."""
    se_ch = max(1, in_ch // 4)
    s = b.add("global_avg_pool", x, name=f"{prefix}_se_squeeze", keepdims=True)
    s = b.add(
        "conv",
        s,
        name=f"{prefix}_se_reduce",
        features=se_ch,
        kernel_size=1,
        use_bias=True,
    )
    s = b.add("swish", s, name=f"{prefix}_se_reduce_swish")
    s = b.add(
        "conv",
        s,
        name=f"{prefix}_se_expand",
        features=expanded_ch,
        kernel_size=1,
        use_bias=True,
    )
    s = b.add("sigmoid", s, name=f"{prefix}_se_sigmoid")
    return b.add("multiply", x, s, name=f"{prefix}_se_excite")


def _mbconv(
    b: GraphBuilder,
    x: str,
    in_ch: int,
    out_ch: int,
    *,
    kernel: int,
    stride: int,
    expansion: int,
    prefix: str,
) -> tuple[str, int]:
    y = x
    expanded = in_ch * expansion
    if expansion != 1:
        y = _conv_bn_swish(b, y, expanded, 1, prefix=f"{prefix}_expand")
    y = b.add(
        "depthwise_conv",
        y,
        name=f"{prefix}_dwconv",
        kernel_size=kernel,
        strides=stride,
        padding="SAME",
        use_bias=False,
    )
    y = b.add("batch_norm", y, name=f"{prefix}_dwconv_bn", eps=1e-3)
    y = b.add("swish", y, name=f"{prefix}_dwconv_swish")
    y = _se_block(b, y, in_ch, expanded, prefix=prefix)
    y = _conv_bn_swish(
        b, y, out_ch, 1, swish=False, prefix=f"{prefix}_project"
    )
    if stride == 1 and in_ch == out_ch:
        # Inference-mode stochastic depth is the identity, so the block
        # reduces to a plain residual add.
        y = b.add("add", x, y, name=f"{prefix}_add")
    return y, out_ch


# (kernel, first-block stride, expansion, out_channels, repeats) — B0.
_B0_SCHEDULE = (
    (3, 1, 1, 16, 1),
    (3, 2, 6, 24, 2),
    (5, 2, 6, 40, 2),
    (3, 2, 6, 80, 3),
    (5, 1, 6, 112, 3),
    (5, 2, 6, 192, 4),
    (3, 1, 6, 320, 1),
)


def _build_efficientnet(
    name: str,
    width_mult: float,
    depth_mult: float,
    resolution: int,
    num_classes: int,
) -> Model:
    b = GraphBuilder(name)
    x = b.input("input")
    ch = _make_divisible(32 * width_mult)
    x = _conv_bn_swish(b, x, ch, 3, strides=2, prefix="stem")

    cuts: list[str] = []
    for gi, (kernel, stride, expansion, out_base, repeats) in enumerate(
        _B0_SCHEDULE, start=1
    ):
        out_ch = _make_divisible(out_base * width_mult)
        for i in range(int(math.ceil(repeats * depth_mult))):
            x, ch = _mbconv(
                b,
                x,
                ch,
                out_ch,
                kernel=kernel,
                stride=stride if i == 0 else 1,
                expansion=expansion,
                prefix=f"block{gi}{chr(ord('a') + i)}",
            )
            cuts.append(x)

    x = _conv_bn_swish(b, x, _make_divisible(1280 * width_mult), 1, prefix="top")
    cuts.append(x)
    x = b.add("global_avg_pool", x, name="avg_pool")
    x = b.add("dense", x, name="predictions_dense", features=num_classes)
    x = b.add("softmax", x, name="predictions")
    return Model(
        name=name,
        graph=b.build(x),
        input_shape=(resolution, resolution, 3),
        cut_candidates=tuple(cuts),
        keras_name_map=_keras_name,
    )


def _keras_name(node: str) -> str:
    """Native node name -> real tf.keras EfficientNet layer name: the
    depthwise BN is plain `{block}_bn` in Keras, and the softmax head
    is the fused `predictions` Dense."""
    if node == "predictions_dense":
        return "predictions"
    if node.endswith("_dwconv_bn"):
        return node[: -len("_dwconv_bn")] + "_bn"
    return node


@register_model("efficientnet_b0")
def efficientnet_b0(num_classes: int = 1000) -> Model:
    return _build_efficientnet("efficientnet_b0", 1.0, 1.0, 224, num_classes)


@register_model("efficientnet_b1")
def efficientnet_b1(num_classes: int = 1000) -> Model:
    return _build_efficientnet("efficientnet_b1", 1.0, 1.1, 240, num_classes)
