"""VGG16/VGG19 — deep sequential CNNs (BASELINE.json: "many partition
cut-points"). Every block-boundary pool output is a valid cut."""

from __future__ import annotations

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model


def _build_vgg(
    name: str, convs_per_block: tuple[int, ...], num_classes: int = 1000
) -> Model:
    widths = (64, 128, 256, 512, 512)
    b = GraphBuilder(name)
    x = b.input("input")
    cuts: list[str] = []
    for blk, (n_convs, width) in enumerate(
        zip(convs_per_block, widths), start=1
    ):
        for i in range(1, n_convs + 1):
            x = b.add(
                "conv",
                x,
                name=f"block{blk}_conv{i}",
                features=width,
                kernel_size=3,
                use_bias=True,
            )
            x = b.add("relu", x, name=f"block{blk}_relu{i}")
            cuts.append(x)
        x = b.add(
            "max_pool", x, name=f"block{blk}_pool", window=2, strides=2
        )
        cuts.append(x)
    x = b.add("flatten", x, name="flatten")
    x = b.add("dense", x, name="fc1", features=4096)
    x = b.add("relu", x, name="fc1_relu")
    x = b.add("dense", x, name="fc2", features=4096)
    x = b.add("relu", x, name="fc2_relu")
    x = b.add("dense", x, name="predictions_dense", features=num_classes)
    x = b.add("softmax", x, name="predictions")
    return Model(
        name=name,
        graph=b.build(x),
        input_shape=(224, 224, 3),
        cut_candidates=tuple(cuts),
        # Node names already match real tf.keras VGG checkpoints
        # (block{b}_conv{i}, fc1, fc2) except the split softmax head.
        keras_name_map=lambda n: (
            "predictions" if n == "predictions_dense" else n
        ),
    )


@register_model("vgg16")
def vgg16(num_classes: int = 1000) -> Model:
    return _build_vgg("vgg16", (2, 2, 3, 3, 3), num_classes)


@register_model("vgg19")
def vgg19(num_classes: int = 1000) -> Model:
    return _build_vgg("vgg19", (2, 2, 4, 4, 4), num_classes)
