"""Llama-family causal decoder — beyond-reference model family.

The reference's zoo stops at Keras CNNs plus our BERT/GPT additions;
the dominant open-weights serving workload is the llama architecture:
RMSNorm, rotary position embeddings, grouped-query attention and a
SwiGLU FFN, all biasless. Here that is a CONFIGURATION of the shared
transformer stack (defer_tpu/parallel/transformer_stack.py), not a
fork: the same KV-cache decoder (defer_tpu/models/gpt.py) serves it,
the same SPMD machinery tensor-parallelizes it, and the GQA cache is
genuinely smaller ([L, B, H_kv, S, Dh] — the architecture's point).

Checkpoint interop mirrors the Keras transplant path the CNN zoo uses
(reference src/node.py:42): `from_hf_state_dict` maps a HuggingFace
`LlamaForCausalLM.state_dict()` onto the stack's pytree, numerically
validated against transformers' own forward in tests/test_llama.py.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from defer_tpu.models.gpt import GptDecoder, SpmdGptDecoder
from defer_tpu.parallel.transformer_stack import TransformerConfig


def llama_config(
    *,
    num_layers: int = 32,
    dim: int = 4096,
    num_heads: int = 32,
    num_kv_heads: int = 8,
    ffn_dim: int = 14336,
    vocab_size: int = 32000,
    max_len: int = 4096,
    rope_theta: float = 10000.0,
    eps: float = 1e-5,
    window: int | None = None,
) -> TransformerConfig:
    """The llama architecture as a TransformerConfig (defaults are
    7B-class shapes; tests use tiny ones)."""
    return TransformerConfig(
        num_layers=num_layers,
        dim=dim,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads,
        ffn_dim=ffn_dim,
        vocab_size=vocab_size,
        max_len=max_len,
        layer_norm_eps=eps,
        norm_style="pre",
        norm_type="rms",
        ffn_style="swiglu",
        pos_style="rope",
        use_bias=False,
        rope_theta=rope_theta,
        causal=True,
        window=window,
    )


def mistral_config(**kw) -> TransformerConfig:
    """Mistral = the llama architecture + sliding-window attention
    (each position attends its last `window` predecessors; default
    4096 as in Mistral-7B). Checkpoints transplant through the same
    `from_hf_state_dict` — HF MistralForCausalLM uses identical
    parameter names."""
    kw.setdefault("window", 4096)
    return llama_config(**kw)


def tiny_llama(seq_len: int = 32) -> GptDecoder:
    """Small llama-shaped decoder for tests / CPU."""
    return GptDecoder(
        llama_config(
            num_layers=2,
            dim=64,
            num_heads=4,
            num_kv_heads=2,
            ffn_dim=128,
            vocab_size=96,
            max_len=seq_len,
        ),
        compute_dtype=jnp.float32,
    )


def spmd_llama(
    mesh: Any,
    cfg: TransformerConfig,
    *,
    compute_dtype: Any = jnp.bfloat16,
    tp_axis: str = "model",
    dp_axis: str | None = None,
) -> SpmdGptDecoder:
    """Tensor-parallel llama serving: head-group-sharded projections
    and GQA caches, vocab-sharded tied head — the SpmdGptDecoder
    machinery, which requires num_kv_heads % tp == 0."""
    return SpmdGptDecoder(
        cfg,
        compute_dtype=compute_dtype,
        mesh=mesh,
        tp_axis=tp_axis,
        dp_axis=dp_axis,
    )


def from_hf_state_dict(
    cfg: TransformerConfig, state_dict: Mapping[str, Any]
) -> dict:
    """Map a HuggingFace `LlamaForCausalLM.state_dict()` onto the
    decoder's param pytree.

    Torch Linear stores [out, in]; the stack computes x @ W with
    [in, out], so every projection transposes. The head is weight-tied
    (`token_embedding`), matching HF's tie_word_embeddings=True; a
    separate lm_head in the checkpoint is ignored with a warning-free
    contract (tied models simply don't ship one).
    """
    L = cfg.num_layers
    dh = cfg.dim // cfg.num_heads

    from defer_tpu.models.transplant import tensor_to_numpy

    def t(name: str) -> np.ndarray:
        return tensor_to_numpy(state_dict[name])

    def proj(i: int, which: str) -> np.ndarray:
        return t(f"model.layers.{i}.self_attn.{which}.weight").T

    def mlp(i: int, which: str) -> np.ndarray:
        return t(f"model.layers.{i}.mlp.{which}.weight").T

    stack = {
        "wq": np.stack([proj(i, "q_proj") for i in range(L)]),
        "wk": np.stack([proj(i, "k_proj") for i in range(L)]),
        "wv": np.stack([proj(i, "v_proj") for i in range(L)]),
        "wo": np.stack([proj(i, "o_proj") for i in range(L)]),
        # w1 = gate (silu branch), w3 = up, w2 = down — the stack's
        # swiglu convention (transformer_stack.block_apply).
        "w1": np.stack([mlp(i, "gate_proj") for i in range(L)]),
        "w3": np.stack([mlp(i, "up_proj") for i in range(L)]),
        "w2": np.stack([mlp(i, "down_proj") for i in range(L)]),
        "ln1_scale": np.stack(
            [
                t(f"model.layers.{i}.input_layernorm.weight")
                for i in range(L)
            ]
        ),
        "ln2_scale": np.stack(
            [
                t(f"model.layers.{i}.post_attention_layernorm.weight")
                for i in range(L)
            ]
        ),
    }
    kv_dim = cfg.kv_heads * dh
    assert stack["wk"].shape == (L, cfg.dim, kv_dim), stack["wk"].shape
    params = {
        "token_embedding": jnp.asarray(t("model.embed_tokens.weight")),
        "final_ln_scale": jnp.asarray(t("model.norm.weight")),
        "stack": {k: jnp.asarray(v) for k, v in stack.items()},
    }
    # Untied checkpoints (tie_word_embeddings=False — real Llama-2/3
    # releases) carry a distinct output head; silently falling back to
    # the tied head would make every logit wrong. Tied checkpoints
    # often still LIST lm_head.weight (it aliases the embedding), so
    # only keep it when the values actually differ.
    if "lm_head.weight" in state_dict:
        head = t("lm_head.weight")
        if not np.array_equal(
            head, np.asarray(params["token_embedding"])
        ):
            params["lm_head"] = jnp.asarray(head)
    return params
