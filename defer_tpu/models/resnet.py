"""ResNet v1 (50/101/152) in the graph IR — the reference's headline model.

The reference benchmarks exactly this network: `ResNet50(weights=
'imagenet')` cut at `add_N` layers (reference src/test.py:23-28,
src/local_infer.py:8). Residual-sum nodes are named `add_1` ... `add_16`
to match the TF1-era Keras auto-naming the reference's cut lists use, so
`part_at = ["add_2", "add_4", ..., "add_14"]` (reference src/test.py:27)
works verbatim. Every add output dominates the downstream graph, making
each a valid single-tensor cut point (SURVEY.md §3.4).
"""

from __future__ import annotations

import re

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model

_BOTTLENECK_RE = re.compile(r"res(\d+)(.)_(a|b|c|proj)_(conv|bn)$")
_PART_IDX = {"proj": 0, "a": 1, "b": 2, "c": 3}


def _keras_name(node: str) -> str:
    """Native node name -> real tf.keras ResNet layer name, e.g.
    `res2a_a_conv` -> `conv2_block1_1_conv`, `res3b_proj_bn` ->
    `conv3_block2_0_bn`, `fc` -> `predictions` (the names
    `ResNet50(weights='imagenet')` checkpoints use, reference
    src/local_infer.py:8)."""
    if node == "fc":
        return "predictions"
    m = _BOTTLENECK_RE.match(node)
    if m:
        group, letter, part, kind = m.groups()
        block = ord(letter) - ord("a") + 1
        return f"conv{group}_block{block}_{_PART_IDX[part]}_{kind}"
    return node


def _conv_bn_relu(
    b: GraphBuilder,
    x: str,
    features: int,
    kernel: int,
    *,
    strides: int = 1,
    padding: str = "SAME",
    relu: bool = True,
    prefix: str,
) -> str:
    x = b.add(
        "conv",
        x,
        name=f"{prefix}_conv",
        features=features,
        kernel_size=kernel,
        strides=strides,
        padding=padding,
        use_bias=False,
    )
    x = b.add("batch_norm", x, name=f"{prefix}_bn", eps=1.001e-5)
    if relu:
        x = b.add("relu", x, name=f"{prefix}_relu")
    return x


def _bottleneck(
    b: GraphBuilder,
    x: str,
    filters: int,
    *,
    strides: int,
    projection: bool,
    prefix: str,
    add_name: str,
) -> str:
    """Standard v1 bottleneck: 1x1 -> 3x3 -> 1x1(4f) + shortcut."""
    shortcut = x
    if projection:
        shortcut = b.add(
            "conv",
            x,
            name=f"{prefix}_proj_conv",
            features=filters * 4,
            kernel_size=1,
            strides=strides,
            padding="VALID",
            use_bias=False,
        )
        shortcut = b.add(
            "batch_norm", shortcut, name=f"{prefix}_proj_bn", eps=1.001e-5
        )
    y = _conv_bn_relu(
        b, x, filters, 1, strides=strides, padding="VALID", prefix=f"{prefix}_a"
    )
    y = _conv_bn_relu(b, y, filters, 3, prefix=f"{prefix}_b")
    y = _conv_bn_relu(
        b, y, filters * 4, 1, padding="VALID", relu=False, prefix=f"{prefix}_c"
    )
    out = b.add("add", y, shortcut, name=add_name)
    return b.add("relu", out, name=f"{add_name}_relu")


def _build_resnet(
    name: str, blocks_per_group: tuple[int, ...], num_classes: int = 1000
) -> Model:
    b = GraphBuilder(name)
    x = b.input("input")
    x = b.add("zero_pad", x, name="conv1_pad", padding=((3, 3), (3, 3)))
    x = _conv_bn_relu(
        b, x, 64, 7, strides=2, padding="VALID", prefix="conv1"
    )
    x = b.add("zero_pad", x, name="pool1_pad", padding=((1, 1), (1, 1)))
    x = b.add(
        "max_pool", x, name="pool1", window=3, strides=2, padding="VALID"
    )

    adds: list[str] = []
    add_idx = 1
    filters = 64
    for group, num_blocks in enumerate(blocks_per_group, start=2):
        for block in range(num_blocks):
            first = block == 0
            x = _bottleneck(
                b,
                x,
                filters,
                # Group 2 keeps stride 1 (the stem's maxpool already
                # downsampled); later groups downsample in their first block.
                strides=2 if (first and group > 2) else 1,
                projection=first,
                prefix=f"res{group}{chr(ord('a') + block)}",
                add_name=f"add_{add_idx}",
            )
            adds.append(f"add_{add_idx}")
            add_idx += 1
        filters *= 2

    x = b.add("global_avg_pool", x, name="avg_pool")
    x = b.add("dense", x, name="fc", features=num_classes)
    x = b.add("softmax", x, name="predictions")
    graph = b.build(x)
    # Cut at the post-add relu so the relu isn't duplicated across stages;
    # `add_N` itself is also valid (it dominates everything downstream).
    return Model(
        name=name,
        graph=graph,
        input_shape=(224, 224, 3),
        cut_candidates=tuple(adds),
        keras_name_map=_keras_name,
    )


@register_model("resnet50")
def resnet50(num_classes: int = 1000) -> Model:
    return _build_resnet("resnet50", (3, 4, 6, 3), num_classes)


@register_model("resnet101")
def resnet101(num_classes: int = 1000) -> Model:
    return _build_resnet("resnet101", (3, 4, 23, 3), num_classes)


@register_model("resnet152")
def resnet152(num_classes: int = 1000) -> Model:
    return _build_resnet("resnet152", (3, 8, 36, 3), num_classes)
