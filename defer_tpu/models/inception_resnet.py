"""inception_resnet — implemented in a later milestone this round."""
