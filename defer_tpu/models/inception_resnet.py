"""InceptionResNetV2 — residual multi-branch DAG (BASELINE.json:
"InceptionResNetV2 / NASNet (multi-branch DAG — stresses dag_util
partitioner)").

The stress is real: each residual block both branches (inception-style
concat) and skips (residual add), so an unvalidated cut through a branch
— which the reference's partitioner would silently miscompile (reference
src/dag_util.py:11-27, SURVEY.md §3.4) — is rejected here, while every
block output remains a valid articulation point.

Uses the `scale` op for the residual scaling the paper applies before
each add (Keras implements it as a Lambda; here it is a first-class op,
defer_tpu/ops/library.py).
"""

from __future__ import annotations

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model
from defer_tpu.models.inception import _cb, _inception_stem


def _residual_block(
    b: GraphBuilder,
    x: str,
    branches: list[str],
    out_ch: int,
    scale: float,
    *,
    name: str,
    relu: bool = True,
) -> str:
    """concat(branches) -> 1x1 linear conv -> *scale -> + x [-> relu].

    The 'up' conv has a bias and no BN, matching the residual family's
    block design.
    """
    mixed = (
        b.add("concat", *branches, name=f"{name}_mixed")
        if len(branches) > 1
        else branches[0]
    )
    up = b.add(
        "conv",
        mixed,
        name=f"{name}_conv",
        features=out_ch,
        kernel_size=1,
        use_bias=True,
    )
    up = b.add("scale", up, name=f"{name}_scale", value=scale)
    out = b.add("add", x, up, name=f"{name}_add")
    if relu:
        out = b.add("relu", out, name=name)
    return out


def _block35(b: GraphBuilder, x: str, scale: float, *, name: str) -> str:
    b0 = _cb(b, x, 32, 1, prefix=f"{name}_b0")
    b1 = _cb(b, x, 32, 1, prefix=f"{name}_b1_0")
    b1 = _cb(b, b1, 32, 3, prefix=f"{name}_b1_1")
    b2 = _cb(b, x, 32, 1, prefix=f"{name}_b2_0")
    b2 = _cb(b, b2, 48, 3, prefix=f"{name}_b2_1")
    b2 = _cb(b, b2, 64, 3, prefix=f"{name}_b2_2")
    return _residual_block(b, x, [b0, b1, b2], 320, scale, name=name)


def _block17(b: GraphBuilder, x: str, scale: float, *, name: str) -> str:
    b0 = _cb(b, x, 192, 1, prefix=f"{name}_b0")
    b1 = _cb(b, x, 128, 1, prefix=f"{name}_b1_0")
    b1 = _cb(b, b1, 160, (1, 7), prefix=f"{name}_b1_1")
    b1 = _cb(b, b1, 192, (7, 1), prefix=f"{name}_b1_2")
    return _residual_block(b, x, [b0, b1], 1088, scale, name=name)


def _block8(
    b: GraphBuilder, x: str, scale: float, *, name: str, relu: bool = True
) -> str:
    b0 = _cb(b, x, 192, 1, prefix=f"{name}_b0")
    b1 = _cb(b, x, 192, 1, prefix=f"{name}_b1_0")
    b1 = _cb(b, b1, 224, (1, 3), prefix=f"{name}_b1_1")
    b1 = _cb(b, b1, 256, (3, 1), prefix=f"{name}_b1_2")
    return _residual_block(b, x, [b0, b1], 2080, scale, name=name, relu=relu)


@register_model("inception_resnet_v2")
def inception_resnet_v2(num_classes: int = 1000) -> Model:
    b = GraphBuilder("inception_resnet_v2")
    x = b.input("input")
    x = _inception_stem(b, x)

    # mixed_5b (Inception-A): -> 35x35x320.
    a0 = _cb(b, x, 96, 1, prefix="mixed_5b_b0")
    a1 = _cb(b, x, 48, 1, prefix="mixed_5b_b1_0")
    a1 = _cb(b, a1, 64, 5, prefix="mixed_5b_b1_1")
    a2 = _cb(b, x, 64, 1, prefix="mixed_5b_b2_0")
    a2 = _cb(b, a2, 96, 3, prefix="mixed_5b_b2_1")
    a2 = _cb(b, a2, 96, 3, prefix="mixed_5b_b2_2")
    ap = b.add(
        "avg_pool", x, name="mixed_5b_pool", window=3, strides=1, padding="SAME"
    )
    ap = _cb(b, ap, 64, 1, prefix="mixed_5b_bpool")
    x = b.add("concat", a0, a1, a2, ap, name="mixed_5b")

    cuts: list[str] = []
    for i in range(1, 11):
        x = _block35(b, x, 0.17, name=f"block35_{i}")
        cuts.append(x)

    # mixed_6a (Reduction-A): -> 17x17x1088.
    r0 = _cb(b, x, 384, 3, strides=2, padding="VALID", prefix="mixed_6a_b0")
    r1 = _cb(b, x, 256, 1, prefix="mixed_6a_b1_0")
    r1 = _cb(b, r1, 256, 3, prefix="mixed_6a_b1_1")
    r1 = _cb(b, r1, 384, 3, strides=2, padding="VALID", prefix="mixed_6a_b1_2")
    rp = b.add(
        "max_pool", x, name="mixed_6a_pool", window=3, strides=2, padding="VALID"
    )
    x = b.add("concat", r0, r1, rp, name="mixed_6a")
    cuts.append(x)

    for i in range(1, 21):
        x = _block17(b, x, 0.1, name=f"block17_{i}")
        cuts.append(x)

    # mixed_7a (Reduction-B): -> 8x8x2080.
    s0 = _cb(b, x, 256, 1, prefix="mixed_7a_b0_0")
    s0 = _cb(b, s0, 384, 3, strides=2, padding="VALID", prefix="mixed_7a_b0_1")
    s1 = _cb(b, x, 256, 1, prefix="mixed_7a_b1_0")
    s1 = _cb(b, s1, 288, 3, strides=2, padding="VALID", prefix="mixed_7a_b1_1")
    s2 = _cb(b, x, 256, 1, prefix="mixed_7a_b2_0")
    s2 = _cb(b, s2, 288, 3, prefix="mixed_7a_b2_1")
    s2 = _cb(b, s2, 320, 3, strides=2, padding="VALID", prefix="mixed_7a_b2_2")
    sp = b.add(
        "max_pool", x, name="mixed_7a_pool", window=3, strides=2, padding="VALID"
    )
    x = b.add("concat", s0, s1, s2, sp, name="mixed_7a")
    cuts.append(x)

    for i in range(1, 10):
        x = _block8(b, x, 0.2, name=f"block8_{i}")
        cuts.append(x)
    x = _block8(b, x, 1.0, name="block8_10", relu=False)
    cuts.append(x)

    x = _cb(b, x, 1536, 1, prefix="conv_7b")
    cuts.append(x)
    x = b.add("global_avg_pool", x, name="avg_pool")
    x = b.add("dense", x, name="predictions_dense", features=num_classes)
    x = b.add("softmax", x, name="predictions")
    return Model(
        name="inception_resnet_v2",
        graph=b.build(x),
        input_shape=(299, 299, 3),
        cut_candidates=tuple(cuts),
    )
