"""T5 encoder-decoder family — beyond-reference model family.

The reference's zoo is single-input CNNs (reference src/test.py:23);
the framework's transformer families so far are encoder-only (BERT/ViT)
and decoder-only (GPT/llama). T5 adds the third architecture class:
a full encoder-decoder with cross-attention and T5's bucketed relative
position bias, built TPU-first:

  * both stacks keep the house layout — params stacked on a leading
    [L] layer axis, applied with `lax.scan` (one compiled block body
    per stack regardless of depth);
  * the relative position bias lives in ONE [num_buckets, H] table per
    stack (T5 computes it in block 0 and shares it; here it is a
    top-level param), materialized once per forward as a [1, H, Tq, Tk]
    additive bias — static shapes, MXU-friendly;
  * incremental decoding uses the same static-buffer KV-cache design
    as models/gpt.py (`lax.dynamic_update_slice`, masks by cache
    position, one compiled T=1 step), plus per-layer cross-attention
    K/V computed ONCE from the encoder output at cache start — the
    encoder-decoder-specific win (cross K/V never change per step);
  * T5 famously does NOT scale attention logits by 1/sqrt(dh) (the
    scale is folded into initialization); full-sequence paths reuse
    `ops.attention.multi_head_attention` by pre-scaling q by dh**0.5
    to cancel its internal scaling, so checkpoints stay bit-faithful.

Checkpoint interop follows the llama pattern (models/llama.py):
`from_hf_state_dict` maps a HuggingFace `T5ForConditionalGeneration
.state_dict()` onto the pytree, numerically validated against
transformers' own forward in tests/test_t5.py.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from defer_tpu.models.gpt import sample_token
from defer_tpu.ops.attention import multi_head_attention
from defer_tpu.parallel.transformer_stack import _rms_norm


@dataclasses.dataclass(frozen=True)
class T5Config:
    num_layers: int = 6  # encoder depth; decoder depth below
    num_decoder_layers: int | None = None  # None = num_layers
    dim: int = 512
    num_heads: int = 8
    head_dim: int = 64  # T5 decouples head_dim from dim/num_heads
    ffn_dim: int = 2048
    vocab_size: int = 32128
    rel_buckets: int = 32
    rel_max_distance: int = 128
    layer_norm_eps: float = 1e-6
    ffn_style: str = "relu"  # "relu" (v1.0) | "gated-gelu" (v1.1)
    # v1.0 ties the LM head to the shared embedding (and scales the
    # decoder output by dim**-0.5 before it); v1.1 ships a separate
    # lm_head and does not scale.
    tie_word_embeddings: bool = True
    max_len: int = 512  # decoder KV-cache bound
    decoder_start_token_id: int = 0  # T5 starts decoding from <pad>

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def dec_layers(self) -> int:
        return self.num_decoder_layers or self.num_layers

    def __post_init__(self):
        if self.ffn_style not in ("relu", "gated-gelu"):
            raise ValueError(
                f"ffn_style={self.ffn_style!r}: must be 'relu' or "
                "'gated-gelu'"
            )
        if self.rel_buckets < 4 or self.rel_buckets % 2:
            raise ValueError(
                f"rel_buckets={self.rel_buckets} must be even and >= 4 "
                "(bidirectional bucketing halves it)"
            )
        if self.rel_max_distance <= self.rel_buckets // 2:
            # Causal bucketing's log range divides by
            # log(max_distance / (num_buckets // 2)); a ratio <= 1
            # makes that zero or negative and the bucket indices NaN.
            raise ValueError(
                f"rel_max_distance={self.rel_max_distance} must exceed "
                f"rel_buckets // 2 = {self.rel_buckets // 2}"
            )


def relative_position_bucket(
    rel: jax.Array,
    *,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """T5's log-spaced relative-position bucketing.

    `rel` = key_position - query_position (any integer shape). Half
    the buckets cover exact small distances, the other half cover
    log-spaced distances out to max_distance; bidirectional mode
    splits the range again by sign. Matches HF transformers'
    `T5Attention._relative_position_bucket` exactly (the transplant
    test depends on it).
    """
    rel = rel.astype(jnp.int32)
    n = num_buckets
    ret = jnp.zeros_like(rel)
    if bidirectional:
        n //= 2
        ret = ret + (rel > 0).astype(jnp.int32) * n
        rel = jnp.abs(rel)
    else:
        rel = -jnp.minimum(rel, 0)
    max_exact = n // 2
    is_small = rel < max_exact
    # Clamp before the log: rel=0 falls in the is_small branch, but a
    # log(0) in the untaken branch would still poison int casting.
    val_large = max_exact + (
        jnp.log(jnp.maximum(rel, 1).astype(jnp.float32) / max_exact)
        / math.log(max_distance / max_exact)
        * (n - max_exact)
    ).astype(jnp.int32)
    val_large = jnp.minimum(val_large, n - 1)
    return ret + jnp.where(is_small, rel, val_large)


def _rel_bias(
    table: jax.Array,
    qpos: jax.Array,
    kpos: jax.Array,
    *,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """[1, H, Tq, Tk] additive attention bias from a [num_buckets, H]
    table and absolute positions."""
    rel = kpos[None, :] - qpos[:, None]  # (Tq, Tk)
    buckets = relative_position_bucket(
        rel,
        bidirectional=bidirectional,
        num_buckets=num_buckets,
        max_distance=max_distance,
    )
    bias = jnp.take(table, buckets, axis=0)  # (Tq, Tk, H)
    return bias.transpose(2, 0, 1)[None].astype(jnp.float32)


@dataclasses.dataclass
class T5:
    """T5 encoder-decoder with KV-cached incremental decoding.

    encode / decode_logits are the full-sequence paths (training &
    the correctness oracle for the cached step); start_cache + step +
    generate are the serving path.
    """

    cfg: T5Config
    compute_dtype: Any = jnp.bfloat16

    # -- params -----------------------------------------------------------

    def init(self, rng: jax.Array) -> dict:
        cfg = self.cfg
        D, I, F = cfg.dim, cfg.inner_dim, cfg.ffn_dim
        ks = iter(jax.random.split(rng, 24))

        def stack(L: int, cross: bool) -> dict:
            s = D**-0.5
            p = {
                "wq": jax.random.normal(next(ks), (L, D, I)) * s,
                "wk": jax.random.normal(next(ks), (L, D, I)) * s,
                "wv": jax.random.normal(next(ks), (L, D, I)) * s,
                "wo": jax.random.normal(next(ks), (L, I, D)) * I**-0.5,
                "ln1_scale": jnp.ones((L, D)),
                "ln2_scale": jnp.ones((L, D)),
                "w1": jax.random.normal(next(ks), (L, D, F)) * s,
                "w2": jax.random.normal(next(ks), (L, F, D)) * F**-0.5,
            }
            if cfg.ffn_style == "gated-gelu":
                p["w3"] = jax.random.normal(next(ks), (L, D, F)) * s
            if cross:
                p.update(
                    {
                        "cq": jax.random.normal(next(ks), (L, D, I)) * s,
                        "ck": jax.random.normal(next(ks), (L, D, I)) * s,
                        "cv": jax.random.normal(next(ks), (L, D, I)) * s,
                        "co": jax.random.normal(next(ks), (L, I, D))
                        * I**-0.5,
                        "lnx_scale": jnp.ones((L, D)),
                    }
                )
            return p

        p = {
            "token_embedding": jax.random.normal(
                next(ks), (cfg.vocab_size, D)
            ),
            "enc_stack": stack(cfg.num_layers, cross=False),
            "dec_stack": stack(cfg.dec_layers, cross=True),
            "enc_rel_bias": jax.random.normal(
                next(ks), (cfg.rel_buckets, cfg.num_heads)
            )
            * 0.1,
            "dec_rel_bias": jax.random.normal(
                next(ks), (cfg.rel_buckets, cfg.num_heads)
            )
            * 0.1,
            "enc_final_ln": jnp.ones((D,)),
            "dec_final_ln": jnp.ones((D,)),
        }
        if not cfg.tie_word_embeddings:
            p["lm_head"] = (
                jax.random.normal(next(ks), (cfg.vocab_size, D)) * D**-0.5
            )
        return p

    def cast_params(self, params: dict) -> dict:
        """Params re-stored in compute_dtype (serving configuration) —
        same contract as GptDecoder.cast_params."""
        return jax.tree_util.tree_map(
            lambda a: a.astype(self.compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            params,
        )

    # -- shared pieces ----------------------------------------------------

    def _ffn(self, p: dict, x: jax.Array) -> jax.Array:
        dt = x.dtype
        if self.cfg.ffn_style == "gated-gelu":
            # T5 v1.1: gelu(wi_0) * wi_1 -> wo. HF's "gated-gelu" maps
            # to gelu_new — the tanh approximation.
            h = jax.nn.gelu(x @ p["w1"].astype(dt), approximate=True) * (
                x @ p["w3"].astype(dt)
            )
        else:
            h = jax.nn.relu(x @ p["w1"].astype(dt))
        return h @ p["w2"].astype(dt)

    def _rms(self, x: jax.Array, scale: jax.Array) -> jax.Array:
        return _rms_norm(x, scale, self.cfg.layer_norm_eps)

    def _attn_full(self, q, k, v, bias, *, causal: bool) -> jax.Array:
        """Full-sequence attention through the shared op. T5 applies NO
        1/sqrt(dh) scaling; pre-scaling q by dh**0.5 cancels the op's
        internal scale exactly."""
        return multi_head_attention(
            q * self.cfg.head_dim**0.5,
            k,
            v,
            num_heads=self.cfg.num_heads,
            bias=bias,
            causal=causal,
            use_pallas=False,  # additive bias forces the XLA path anyway
        )

    # -- encoder ----------------------------------------------------------

    def encode(self, params: dict, ids: jax.Array) -> jax.Array:
        """[B, S] token ids -> [B, S, D] encoder output (final-LN'd)."""
        cfg = self.cfg
        cd = self.compute_dtype
        x = jnp.take(params["token_embedding"], ids, axis=0).astype(cd)
        pos = jnp.arange(ids.shape[1])
        bias = _rel_bias(
            params["enc_rel_bias"],
            pos,
            pos,
            bidirectional=True,
            num_buckets=cfg.rel_buckets,
            max_distance=cfg.rel_max_distance,
        )

        def block(x, p):
            dt = x.dtype
            h = self._rms(x, p["ln1_scale"])
            attn = self._attn_full(
                h @ p["wq"].astype(dt),
                h @ p["wk"].astype(dt),
                h @ p["wv"].astype(dt),
                bias,
                causal=False,
            )
            x = x + attn @ p["wo"].astype(dt)
            x = x + self._ffn(p, self._rms(x, p["ln2_scale"]))
            return x, None

        x, _ = lax.scan(block, x, params["enc_stack"])
        return self._rms(x, params["enc_final_ln"])

    # -- decoder (full sequence — training / oracle) ----------------------

    def decode_logits(
        self, params: dict, enc_out: jax.Array, dec_ids: jax.Array
    ) -> jax.Array:
        """Teacher-forced decoder: [B, Senc, D] x [B, Tdec] ->
        [B, Tdec, V] fp32 logits."""
        cfg = self.cfg
        cd = self.compute_dtype
        x = jnp.take(params["token_embedding"], dec_ids, axis=0).astype(cd)
        enc_out = enc_out.astype(cd)
        pos = jnp.arange(dec_ids.shape[1])
        self_bias = _rel_bias(
            params["dec_rel_bias"],
            pos,
            pos,
            bidirectional=False,
            num_buckets=cfg.rel_buckets,
            max_distance=cfg.rel_max_distance,
        )

        def block(x, p):
            dt = x.dtype
            h = self._rms(x, p["ln1_scale"])
            attn = self._attn_full(
                h @ p["wq"].astype(dt),
                h @ p["wk"].astype(dt),
                h @ p["wv"].astype(dt),
                self_bias,
                causal=True,
            )
            x = x + attn @ p["wo"].astype(dt)
            h = self._rms(x, p["lnx_scale"])
            cross = self._attn_full(
                h @ p["cq"].astype(dt),
                enc_out @ p["ck"].astype(dt),
                enc_out @ p["cv"].astype(dt),
                None,
                causal=False,
            )
            x = x + cross @ p["co"].astype(dt)
            x = x + self._ffn(p, self._rms(x, p["ln2_scale"]))
            return x, None

        x, _ = lax.scan(block, x, params["dec_stack"])
        x = self._rms(x, params["dec_final_ln"])
        return self._head(params, x)

    def _head(self, params: dict, x: jax.Array) -> jax.Array:
        xf = x.astype(jnp.float32)
        if self.cfg.tie_word_embeddings:
            xf = xf * self.cfg.dim**-0.5
        head = params.get("lm_head", params["token_embedding"])
        return xf @ head.astype(jnp.float32).T

    def forward(
        self, params: dict, enc_ids: jax.Array, dec_ids: jax.Array
    ) -> jax.Array:
        """encode + teacher-forced decode in one call (the training
        forward): [B, S] x [B, T] -> [B, T, V] logits."""
        return self.decode_logits(params, self.encode(params, enc_ids), dec_ids)

    # -- incremental decoding --------------------------------------------

    def start_cache(self, params: dict, enc_out: jax.Array) -> dict:
        """Serving cache for one encoded batch: empty self-attention
        K/V buffers plus the cross-attention K/V of every decoder
        layer, projected ONCE from the encoder output (they are
        constant for the whole generation — the encoder-decoder-
        specific saving; recomputing them per token would re-read
        ck/cv and the encoder output every step)."""
        cfg = self.cfg
        cd = self.compute_dtype
        b = enc_out.shape[0]
        enc_out = enc_out.astype(cd)
        H, dh = cfg.num_heads, cfg.head_dim
        cross_k, cross_v = self._project_cross(params, enc_out)
        return {
            "k": jnp.zeros(
                (cfg.dec_layers, b, H, cfg.max_len, dh), cd
            ),
            "v": jnp.zeros(
                (cfg.dec_layers, b, H, cfg.max_len, dh), cd
            ),
            "cross_k": cross_k,
            "cross_v": cross_v,
            "pos": jnp.zeros((), jnp.int32),
        }

    def _project_cross(self, params: dict, enc_out: jax.Array):
        """[L, B, H, Senc, Dh] cross K/V for all decoder layers (one
        batched einsum per projection)."""
        cfg = self.cfg
        cd = enc_out.dtype
        b, s_enc, _ = enc_out.shape
        H, dh = cfg.num_heads, cfg.head_dim
        ck = jnp.einsum(
            "bsd,ldi->lbsi", enc_out, params["dec_stack"]["ck"].astype(cd)
        )
        cv = jnp.einsum(
            "bsd,ldi->lbsi", enc_out, params["dec_stack"]["cv"].astype(cd)
        )
        shape = (cfg.dec_layers, b, s_enc, H, dh)
        return (
            ck.reshape(shape).transpose(0, 1, 3, 2, 4),
            cv.reshape(shape).transpose(0, 1, 3, 2, 4),
        )

    def make_encode(self):
        """Jitted (params, enc_ids) -> (enc_out, fresh serving cache):
        the encoder scan and the per-layer cross-K/V projection compile
        into ONE program (generate's eager path would otherwise pay
        per-op dispatch for the whole encoder every call)."""
        from defer_tpu.utils.memo import cached_step

        def build():
            def fn(params, ids):
                enc_out = self.encode(params, ids)
                return enc_out, self.start_cache(params, enc_out)

            return jax.jit(fn)

        return cached_step(self, "encode", build)

    def prefill(
        self, params: dict, cache: dict, ids: jax.Array
    ) -> tuple[jax.Array, dict]:
        """Consume [B, T] decoder ids into the cache; returns
        (last_logits [B, V], cache). This is the GUARDED entry for
        multi-token steps: the jitted step cannot check the write
        head, and `lax.dynamic_update_slice` CLAMPS an out-of-range
        start — an unguarded overflow would silently overwrite live
        cache rows (same hazard gpt.py's prefill guards)."""
        base = int(jax.device_get(cache["pos"]))
        t = ids.shape[1]
        if base + t > self.cfg.max_len:
            raise ValueError(
                f"cache position {base} + {t} tokens exceeds max_len "
                f"{self.cfg.max_len}"
            )
        logits, cache = self.make_step()(params, cache, ids)
        return logits[:, -1, :], cache

    def make_step(self, *, donate: bool = True):
        """Jitted (params, cache, ids [B, T]) -> (logits [B, T, V],
        cache): the incremental decode step (prefill T>=1 or decode
        T=1), static cache buffers, masks by cache position. The
        caller must keep pos + T <= max_len (use `prefill` for the
        guarded multi-token entry)."""
        from defer_tpu.utils.memo import cached_step

        cfg = self.cfg
        cd = self.compute_dtype
        H, dh = cfg.num_heads, cfg.head_dim

        def step(params, cache, ids):
            b, t = ids.shape
            pos = cache["pos"]
            x = jnp.take(params["token_embedding"], ids, axis=0).astype(cd)
            qpos = pos + jnp.arange(t)
            kpos = jnp.arange(cfg.max_len)
            self_bias = _rel_bias(
                params["dec_rel_bias"],
                qpos,
                kpos,
                bidirectional=False,
                num_buckets=cfg.rel_buckets,
                max_distance=cfg.rel_max_distance,
            )
            # Causal-by-position over the static cache: query at
            # absolute pos+i sees slot j iff j <= pos+i.
            mask = kpos[None, :] <= qpos[:, None]  # (T, S_max)
            self_bias = jnp.where(mask[None, None], self_bias, -jnp.inf)

            def split(t_flat):
                return t_flat.reshape(b, t, H, dh).transpose(0, 2, 1, 3)

            def block(carry, layer):
                x = carry
                p, kc, vc, ck, cv = layer
                dt = x.dtype
                h = self._rms(x, p["ln1_scale"])
                q = split(h @ p["wq"].astype(dt))
                k = split(h @ p["wk"].astype(dt))
                v = split(h @ p["wv"].astype(dt))
                kc = lax.dynamic_update_slice(kc, k, (0, 0, pos, 0))
                vc = lax.dynamic_update_slice(vc, v, (0, 0, pos, 0))
                # T5: NO 1/sqrt(dh) scaling on the logits.
                logits = jnp.einsum(
                    "bhtd,bhsd->bhts",
                    q,
                    kc,
                    preferred_element_type=jnp.float32,
                )
                logits = logits + self_bias
                w = jax.nn.softmax(logits, axis=-1).astype(dt)
                attn = jnp.einsum("bhts,bhsd->bhtd", w, vc)
                attn = attn.transpose(0, 2, 1, 3).reshape(b, t, H * dh)
                x = x + attn @ p["wo"].astype(dt)
                # Cross-attention against the precomputed encoder K/V
                # (no bias, no mask — every encoder position visible).
                h = self._rms(x, p["lnx_scale"])
                q = split(h @ p["cq"].astype(dt))
                logits = jnp.einsum(
                    "bhtd,bhsd->bhts",
                    q,
                    ck,
                    preferred_element_type=jnp.float32,
                )
                w = jax.nn.softmax(logits, axis=-1).astype(dt)
                cross = jnp.einsum("bhts,bhsd->bhtd", w, cv)
                cross = cross.transpose(0, 2, 1, 3).reshape(b, t, H * dh)
                x = x + cross @ p["co"].astype(dt)
                x = x + self._ffn(p, self._rms(x, p["ln2_scale"]))
                return x, (kc, vc)

            x, (new_k, new_v) = lax.scan(
                block,
                x,
                (
                    params["dec_stack"],
                    cache["k"],
                    cache["v"],
                    cache["cross_k"],
                    cache["cross_v"],
                ),
            )
            x = self._rms(x, params["dec_final_ln"])
            new_cache = {
                **cache,
                "k": new_k,
                "v": new_v,
                "pos": pos + t,
            }
            return self._head(params, x), new_cache

        return cached_step(
            self,
            donate,
            lambda: jax.jit(step, donate_argnums=(1,) if donate else ()),
        )

    def generate(
        self,
        params: dict,
        enc_ids: jax.Array,
        num_steps: int,
        *,
        temperature: float = 0.0,
        rng: jax.Array | None = None,
    ) -> jax.Array:
        """Encode once, then greedy/sampled decoding from the start
        token: [B, Senc] -> [B, 1 + num_steps] decoder ids (leading
        start token included)."""
        cfg = self.cfg
        if num_steps + 1 > cfg.max_len:
            raise ValueError(
                f"{num_steps} steps + start token exceeds max_len "
                f"{cfg.max_len}"
            )
        b = enc_ids.shape[0]
        _, cache = self.make_encode()(params, enc_ids)
        step = self.make_step()
        ids = jnp.full((b, 1), cfg.decoder_start_token_id, jnp.int32)
        if rng is None:
            rng = jax.random.key(0)
        last, cache = self.prefill(params, cache, ids)
        for i in range(num_steps):
            nxt, rng = sample_token(last, rng, temperature)
            nxt = nxt[:, None].astype(jnp.int32)
            ids = jnp.concatenate([ids, nxt], axis=1)
            if i + 1 < num_steps:
                logits, cache = step(params, cache, nxt)
                last = logits[:, -1, :]
        return ids


def t5_config(name: str = "small", **overrides: Any) -> T5Config:
    """Named T5 shapes ("small", "base", "large") with overrides."""
    shapes = {
        "small": dict(num_layers=6, dim=512, num_heads=8, ffn_dim=2048),
        "base": dict(num_layers=12, dim=768, num_heads=12, ffn_dim=3072),
        "large": dict(
            num_layers=24, dim=1024, num_heads=16, ffn_dim=4096
        ),
    }
    if name not in shapes:
        raise KeyError(f"unknown t5 size {name!r}; have {sorted(shapes)}")
    kw: dict[str, Any] = dict(shapes[name])
    kw.update(overrides)
    return T5Config(**kw)


def tiny_t5(**overrides: Any) -> T5:
    """Small config for tests / CPU."""
    kw: dict[str, Any] = dict(
        num_layers=2,
        dim=32,
        num_heads=4,
        head_dim=8,
        ffn_dim=64,
        vocab_size=96,
        rel_buckets=8,
        rel_max_distance=20,
        max_len=32,
    )
    kw.update(overrides)
    return T5(T5Config(**kw), compute_dtype=jnp.float32)


def from_hf_state_dict(cfg: T5Config, state_dict: Mapping[str, Any]) -> dict:
    """Map a HuggingFace `T5ForConditionalGeneration.state_dict()` onto
    the T5 param pytree (torch Linear stores [out, in]; the stacks
    compute x @ W with [in, out], so projections transpose)."""

    from defer_tpu.models.transplant import tensor_to_numpy

    def t(name: str) -> np.ndarray:
        return tensor_to_numpy(state_dict[name])

    def attn(side: str, i: int, layer: int, which: str) -> np.ndarray:
        mod = "SelfAttention" if layer == 0 else "EncDecAttention"
        return t(f"{side}.block.{i}.layer.{layer}.{mod}.{which}.weight").T

    def ffn(side: str, i: int, layer: int, which: str) -> np.ndarray:
        return t(
            f"{side}.block.{i}.layer.{layer}.DenseReluDense.{which}.weight"
        ).T

    def ln(side: str, i: int, layer: int) -> np.ndarray:
        return t(f"{side}.block.{i}.layer.{layer}.layer_norm.weight")

    gated = cfg.ffn_style == "gated-gelu"
    wi = "wi_0" if gated else "wi"

    def stack(side: str, L: int, cross: bool) -> dict:
        ffn_layer = 2 if cross else 1
        p = {
            "wq": np.stack([attn(side, i, 0, "q") for i in range(L)]),
            "wk": np.stack([attn(side, i, 0, "k") for i in range(L)]),
            "wv": np.stack([attn(side, i, 0, "v") for i in range(L)]),
            "wo": np.stack([attn(side, i, 0, "o") for i in range(L)]),
            "ln1_scale": np.stack([ln(side, i, 0) for i in range(L)]),
            "ln2_scale": np.stack(
                [ln(side, i, ffn_layer) for i in range(L)]
            ),
            "w1": np.stack([ffn(side, i, ffn_layer, wi) for i in range(L)]),
            "w2": np.stack(
                [ffn(side, i, ffn_layer, "wo") for i in range(L)]
            ),
        }
        if gated:
            p["w3"] = np.stack(
                [ffn(side, i, ffn_layer, "wi_1") for i in range(L)]
            )
        if cross:
            p.update(
                {
                    "cq": np.stack(
                        [attn(side, i, 1, "q") for i in range(L)]
                    ),
                    "ck": np.stack(
                        [attn(side, i, 1, "k") for i in range(L)]
                    ),
                    "cv": np.stack(
                        [attn(side, i, 1, "v") for i in range(L)]
                    ),
                    "co": np.stack(
                        [attn(side, i, 1, "o") for i in range(L)]
                    ),
                    "lnx_scale": np.stack(
                        [ln(side, i, 1) for i in range(L)]
                    ),
                }
            )
        return {k: jnp.asarray(v) for k, v in p.items()}

    params = {
        "token_embedding": jnp.asarray(t("shared.weight")),
        "enc_stack": stack("encoder", cfg.num_layers, cross=False),
        "dec_stack": stack("decoder", cfg.dec_layers, cross=True),
        "enc_rel_bias": jnp.asarray(
            t(
                "encoder.block.0.layer.0.SelfAttention"
                ".relative_attention_bias.weight"
            )
        ),
        "dec_rel_bias": jnp.asarray(
            t(
                "decoder.block.0.layer.0.SelfAttention"
                ".relative_attention_bias.weight"
            )
        ),
        "enc_final_ln": jnp.asarray(t("encoder.final_layer_norm.weight")),
        "dec_final_ln": jnp.asarray(t("decoder.final_layer_norm.weight")),
    }
    if "lm_head.weight" in state_dict:
        head = t("lm_head.weight")
        if not np.array_equal(head, np.asarray(params["token_embedding"])):
            params["lm_head"] = jnp.asarray(head)
    return params
