"""T5 encoder-decoder family — beyond-reference model family.

The reference's zoo is single-input CNNs (reference src/test.py:23);
the framework's transformer families so far are encoder-only (BERT/ViT)
and decoder-only (GPT/llama). T5 adds the third architecture class:
a full encoder-decoder with cross-attention and T5's bucketed relative
position bias, built TPU-first:

  * both stacks keep the house layout — params stacked on a leading
    [L] layer axis, applied with `lax.scan` (one compiled block body
    per stack regardless of depth);
  * the relative position bias lives in ONE [num_buckets, H] table per
    stack (T5 computes it in block 0 and shares it; here it is a
    top-level param), materialized once per forward as a [1, H, Tq, Tk]
    additive bias — static shapes, MXU-friendly;
  * incremental decoding uses the same static-buffer KV-cache design
    as models/gpt.py (`lax.dynamic_update_slice`, masks by cache
    position, one compiled T=1 step), plus per-layer cross-attention
    K/V computed ONCE from the encoder output at cache start — the
    encoder-decoder-specific win (cross K/V never change per step);
  * T5 famously does NOT scale attention logits by 1/sqrt(dh) (the
    scale is folded into initialization); full-sequence paths reuse
    `ops.attention.multi_head_attention` by pre-scaling q by dh**0.5
    to cancel its internal scaling, so checkpoints stay bit-faithful.

Checkpoint interop follows the llama pattern (models/llama.py):
`from_hf_state_dict` maps a HuggingFace `T5ForConditionalGeneration
.state_dict()` onto the pytree, numerically validated against
transformers' own forward in tests/test_t5.py.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from defer_tpu.models.gpt import sampled_decode_loop
from defer_tpu.ops.attention import multi_head_attention
from defer_tpu.parallel.transformer_stack import _rms_norm, embed_lookup


@dataclasses.dataclass(frozen=True)
class T5Config:
    num_layers: int = 6  # encoder depth; decoder depth below
    num_decoder_layers: int | None = None  # None = num_layers
    dim: int = 512
    num_heads: int = 8
    head_dim: int = 64  # T5 decouples head_dim from dim/num_heads
    ffn_dim: int = 2048
    vocab_size: int = 32128
    rel_buckets: int = 32
    rel_max_distance: int = 128
    layer_norm_eps: float = 1e-6
    ffn_style: str = "relu"  # "relu" (v1.0) | "gated-gelu" (v1.1)
    # v1.0 ties the LM head to the shared embedding (and scales the
    # decoder output by dim**-0.5 before it); v1.1 ships a separate
    # lm_head and does not scale.
    tie_word_embeddings: bool = True
    max_len: int = 512  # decoder KV-cache bound
    decoder_start_token_id: int = 0  # T5 starts decoding from <pad>

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def dec_layers(self) -> int:
        return self.num_decoder_layers or self.num_layers

    def __post_init__(self):
        if self.ffn_style not in ("relu", "gated-gelu"):
            raise ValueError(
                f"ffn_style={self.ffn_style!r}: must be 'relu' or "
                "'gated-gelu'"
            )
        if self.rel_buckets < 4 or self.rel_buckets % 2:
            raise ValueError(
                f"rel_buckets={self.rel_buckets} must be even and >= 4 "
                "(bidirectional bucketing halves it)"
            )
        if self.rel_max_distance <= self.rel_buckets // 2:
            # Causal bucketing's log range divides by
            # log(max_distance / (num_buckets // 2)); a ratio <= 1
            # makes that zero or negative and the bucket indices NaN.
            raise ValueError(
                f"rel_max_distance={self.rel_max_distance} must exceed "
                f"rel_buckets // 2 = {self.rel_buckets // 2}"
            )


def relative_position_bucket(
    rel: jax.Array,
    *,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """T5's log-spaced relative-position bucketing.

    `rel` = key_position - query_position (any integer shape). Half
    the buckets cover exact small distances, the other half cover
    log-spaced distances out to max_distance; bidirectional mode
    splits the range again by sign. Matches HF transformers'
    `T5Attention._relative_position_bucket` exactly (the transplant
    test depends on it).
    """
    rel = rel.astype(jnp.int32)
    n = num_buckets
    ret = jnp.zeros_like(rel)
    if bidirectional:
        n //= 2
        ret = ret + (rel > 0).astype(jnp.int32) * n
        rel = jnp.abs(rel)
    else:
        rel = -jnp.minimum(rel, 0)
    max_exact = n // 2
    is_small = rel < max_exact
    # Clamp before the log: rel=0 falls in the is_small branch, but a
    # log(0) in the untaken branch would still poison int casting.
    val_large = max_exact + (
        jnp.log(jnp.maximum(rel, 1).astype(jnp.float32) / max_exact)
        / math.log(max_distance / max_exact)
        * (n - max_exact)
    ).astype(jnp.int32)
    val_large = jnp.minimum(val_large, n - 1)
    return ret + jnp.where(is_small, rel, val_large)


def _rel_bias(
    table: jax.Array,
    qpos: jax.Array,
    kpos: jax.Array,
    *,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """[1, H, Tq, Tk] additive attention bias from a [num_buckets, H]
    table and absolute positions."""
    rel = kpos[None, :] - qpos[:, None]  # (Tq, Tk)
    buckets = relative_position_bucket(
        rel,
        bidirectional=bidirectional,
        num_buckets=num_buckets,
        max_distance=max_distance,
    )
    bias = jnp.take(table, buckets, axis=0)  # (Tq, Tk, H)
    return bias.transpose(2, 0, 1)[None].astype(jnp.float32)


@dataclasses.dataclass
class T5:
    """T5 encoder-decoder with KV-cached incremental decoding.

    encode / decode_logits are the full-sequence paths (training &
    the correctness oracle for the cached step); start_cache + step +
    generate are the serving path.
    """

    cfg: T5Config
    compute_dtype: Any = jnp.bfloat16

    # -- params -----------------------------------------------------------

    def init(self, rng: jax.Array) -> dict:
        cfg = self.cfg
        D, I, F = cfg.dim, cfg.inner_dim, cfg.ffn_dim
        ks = iter(jax.random.split(rng, 24))

        def stack(L: int, cross: bool) -> dict:
            s = D**-0.5
            p = {
                "wq": jax.random.normal(next(ks), (L, D, I)) * s,
                "wk": jax.random.normal(next(ks), (L, D, I)) * s,
                "wv": jax.random.normal(next(ks), (L, D, I)) * s,
                "wo": jax.random.normal(next(ks), (L, I, D)) * I**-0.5,
                "ln1_scale": jnp.ones((L, D)),
                "ln2_scale": jnp.ones((L, D)),
                "w1": jax.random.normal(next(ks), (L, D, F)) * s,
                "w2": jax.random.normal(next(ks), (L, F, D)) * F**-0.5,
            }
            if cfg.ffn_style == "gated-gelu":
                p["w3"] = jax.random.normal(next(ks), (L, D, F)) * s
            if cross:
                p.update(
                    {
                        "cq": jax.random.normal(next(ks), (L, D, I)) * s,
                        "ck": jax.random.normal(next(ks), (L, D, I)) * s,
                        "cv": jax.random.normal(next(ks), (L, D, I)) * s,
                        "co": jax.random.normal(next(ks), (L, I, D))
                        * I**-0.5,
                        "lnx_scale": jnp.ones((L, D)),
                    }
                )
            return p

        p = {
            "token_embedding": jax.random.normal(
                next(ks), (cfg.vocab_size, D)
            ),
            "enc_stack": stack(cfg.num_layers, cross=False),
            "dec_stack": stack(cfg.dec_layers, cross=True),
            "enc_rel_bias": jax.random.normal(
                next(ks), (cfg.rel_buckets, cfg.num_heads)
            )
            * 0.1,
            "dec_rel_bias": jax.random.normal(
                next(ks), (cfg.rel_buckets, cfg.num_heads)
            )
            * 0.1,
            "enc_final_ln": jnp.ones((D,)),
            "dec_final_ln": jnp.ones((D,)),
        }
        if not cfg.tie_word_embeddings:
            p["lm_head"] = (
                jax.random.normal(next(ks), (cfg.vocab_size, D)) * D**-0.5
            )
        return p

    def cast_params(self, params: dict) -> dict:
        """Params re-stored in compute_dtype (serving configuration) —
        same contract as GptDecoder.cast_params."""
        return jax.tree_util.tree_map(
            lambda a: a.astype(self.compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            params,
        )

    # -- shared pieces ----------------------------------------------------

    def _ffn(
        self, p: dict, x: jax.Array, tp_axis: str | None = None
    ) -> jax.Array:
        dt = x.dtype
        if self.cfg.ffn_style == "gated-gelu":
            # T5 v1.1: gelu(wi_0) * wi_1 -> wo. HF's "gated-gelu" maps
            # to gelu_new — the tanh approximation.
            h = jax.nn.gelu(x @ p["w1"].astype(dt), approximate=True) * (
                x @ p["w3"].astype(dt)
            )
        else:
            h = jax.nn.relu(x @ p["w1"].astype(dt))
        out = h @ p["w2"].astype(dt)
        if tp_axis is not None:
            # w1/w3 column-, w2 row-sharded: partial sums over tp.
            out = lax.psum(out, tp_axis)
        return out

    def _rms(self, x: jax.Array, scale: jax.Array) -> jax.Array:
        return _rms_norm(x, scale, self.cfg.layer_norm_eps)

    def _attn_full(self, q, k, v, bias, *, causal: bool) -> jax.Array:
        """Full-sequence attention through the shared op. T5 applies NO
        1/sqrt(dh) scaling; pre-scaling q by dh**0.5 cancels the op's
        internal scale exactly. Head count is inferred from the actual
        projection width, so tensor-parallel shards (one head group
        each) pass through unchanged."""
        return multi_head_attention(
            q * self.cfg.head_dim**0.5,
            k,
            v,
            num_heads=q.shape[-1] // self.cfg.head_dim,
            bias=bias,
            causal=causal,
            use_pallas=False,  # additive bias forces the XLA path anyway
        )

    def _embed(
        self, params: dict, ids: jax.Array, tp_axis: str | None
    ) -> jax.Array:
        """Token embedding in compute dtype (shared Megatron-sharded
        gather, parallel/transformer_stack.embed_lookup)."""
        return embed_lookup(
            params["token_embedding"], ids, tp_axis
        ).astype(self.compute_dtype)

    # -- encoder ----------------------------------------------------------

    @staticmethod
    def _key_mask_bias(mask: jax.Array | None) -> jax.Array | None:
        """[B, S] validity mask (1 = real token) -> [B, 1, 1, S]
        additive attention bias masking pad KEY positions. A large
        finite constant, not -inf (HF's convention): an ALL-pad row
        (zero-length input in a ragged batch) then softmaxes to
        uniform garbage instead of NaN that would poison the whole
        forward."""
        if mask is None:
            return None
        return jnp.where(
            mask.astype(bool)[:, None, None, :], 0.0, -1e9
        )

    def encode(
        self,
        params: dict,
        ids: jax.Array,
        tp_axis: str | None = None,
        *,
        mask: jax.Array | None = None,
    ) -> jax.Array:
        """[B, S] token ids -> [B, S, D] encoder output (final-LN'd).

        `mask` [B, S] (1 = real token) excludes pad KEY positions from
        every self-attention — required for batched variable-length
        inputs padded to a common length (pad rows of the OUTPUT are
        garbage; downstream cross-attention must mask them too, which
        the decoder paths do when given the same mask).

        With tp_axis set (inside shard_map), projections arrive
        column-sharded as one head group per shard — the rel-bias
        table's local width picks the matching head slice — and
        wo/w2 row-sharded with psum (the Megatron pattern)."""
        cfg = self.cfg
        x = self._embed(params, ids, tp_axis)
        pos = jnp.arange(ids.shape[1])
        bias = _rel_bias(
            params["enc_rel_bias"],
            pos,
            pos,
            bidirectional=True,
            num_buckets=cfg.rel_buckets,
            max_distance=cfg.rel_max_distance,
        )
        kb = self._key_mask_bias(mask)
        if kb is not None:
            bias = bias + kb

        def block(x, p):
            dt = x.dtype
            h = self._rms(x, p["ln1_scale"])
            attn = self._attn_full(
                h @ p["wq"].astype(dt),
                h @ p["wk"].astype(dt),
                h @ p["wv"].astype(dt),
                bias,
                causal=False,
            )
            attn = attn @ p["wo"].astype(dt)
            if tp_axis is not None:
                attn = lax.psum(attn, tp_axis)
            x = x + attn
            x = x + self._ffn(p, self._rms(x, p["ln2_scale"]), tp_axis)
            return x, None

        x, _ = lax.scan(block, x, params["enc_stack"])
        return self._rms(x, params["enc_final_ln"])

    # -- decoder (full sequence — training / oracle) ----------------------

    def decode_logits(
        self,
        params: dict,
        enc_out: jax.Array,
        dec_ids: jax.Array,
        tp_axis: str | None = None,
        *,
        enc_mask: jax.Array | None = None,
    ) -> jax.Array:
        """Teacher-forced decoder: [B, Senc, D] x [B, Tdec] ->
        [B, Tdec, V] fp32 logits (the local vocab slice under tp).
        `enc_mask` [B, Senc] excludes pad encoder positions from every
        cross-attention (pass the mask given to encode)."""
        cfg = self.cfg
        cd = self.compute_dtype
        x = self._embed(params, dec_ids, tp_axis)
        enc_out = enc_out.astype(cd)
        cross_bias = self._key_mask_bias(enc_mask)
        pos = jnp.arange(dec_ids.shape[1])
        self_bias = _rel_bias(
            params["dec_rel_bias"],
            pos,
            pos,
            bidirectional=False,
            num_buckets=cfg.rel_buckets,
            max_distance=cfg.rel_max_distance,
        )

        def block(x, p):
            dt = x.dtype
            h = self._rms(x, p["ln1_scale"])
            attn = self._attn_full(
                h @ p["wq"].astype(dt),
                h @ p["wk"].astype(dt),
                h @ p["wv"].astype(dt),
                self_bias,
                causal=True,
            )
            attn = attn @ p["wo"].astype(dt)
            if tp_axis is not None:
                attn = lax.psum(attn, tp_axis)
            x = x + attn
            h = self._rms(x, p["lnx_scale"])
            cross = self._attn_full(
                h @ p["cq"].astype(dt),
                enc_out @ p["ck"].astype(dt),
                enc_out @ p["cv"].astype(dt),
                cross_bias,
                causal=False,
            )
            cross = cross @ p["co"].astype(dt)
            if tp_axis is not None:
                cross = lax.psum(cross, tp_axis)
            x = x + cross
            x = x + self._ffn(p, self._rms(x, p["ln2_scale"]), tp_axis)
            return x, None

        x, _ = lax.scan(block, x, params["dec_stack"])
        x = self._rms(x, params["dec_final_ln"])
        return self._head(params, x)

    def _head(self, params: dict, x: jax.Array) -> jax.Array:
        """LM head. Under tp the head rows are the local vocab shard,
        so this produces the local logits slice; the shard_map caller's
        out_specs concatenate the slices into global logits."""
        xf = x.astype(jnp.float32)
        if self.cfg.tie_word_embeddings:
            xf = xf * self.cfg.dim**-0.5
        head = params.get("lm_head", params["token_embedding"])
        return xf @ head.astype(jnp.float32).T

    def forward(
        self,
        params: dict,
        enc_ids: jax.Array,
        dec_ids: jax.Array,
        *,
        enc_mask: jax.Array | None = None,
    ) -> jax.Array:
        """encode + teacher-forced decode in one call (the training
        forward): [B, S] x [B, T] -> [B, T, V] logits."""
        enc_out = self.encode(params, enc_ids, mask=enc_mask)
        return self.decode_logits(
            params, enc_out, dec_ids, enc_mask=enc_mask
        )

    # -- incremental decoding --------------------------------------------

    def start_cache(
        self,
        params: dict,
        enc_out: jax.Array,
        enc_mask: jax.Array | None = None,
    ) -> dict:
        """Serving cache for one encoded batch: empty self-attention
        K/V buffers plus the cross-attention K/V of every decoder
        layer, projected ONCE from the encoder output (they are
        constant for the whole generation — the encoder-decoder-
        specific saving; recomputing them per token would re-read
        ck/cv and the encoder output every step). `enc_mask` [B, Senc]
        bakes the pad-key exclusion into the cache as an additive
        cross-attention bias."""
        cfg = self.cfg
        cd = self.compute_dtype
        b, s_enc, _ = enc_out.shape
        enc_out = enc_out.astype(cd)
        cross_bias = self._key_mask_bias(enc_mask)
        if cross_bias is None:
            cross_bias = jnp.zeros((b, 1, 1, s_enc), jnp.float32)
        # Local head count from the actual projection width: under tp
        # each shard caches only its own head group.
        dh = cfg.head_dim
        H = params["dec_stack"]["wk"].shape[-1] // dh
        cross_k, cross_v = self._project_cross(params, enc_out)
        return {
            "k": jnp.zeros(
                (cfg.dec_layers, b, H, cfg.max_len, dh), cd
            ),
            "v": jnp.zeros(
                (cfg.dec_layers, b, H, cfg.max_len, dh), cd
            ),
            "cross_k": cross_k,
            "cross_v": cross_v,
            "cross_bias": cross_bias,
            "pos": jnp.zeros((), jnp.int32),
        }

    def _project_cross(self, params: dict, enc_out: jax.Array):
        """[L, B, H, Senc, Dh] cross K/V for all decoder layers (one
        batched einsum per projection; H = local heads under tp)."""
        cfg = self.cfg
        cd = enc_out.dtype
        b, s_enc, _ = enc_out.shape
        dh = cfg.head_dim
        H = params["dec_stack"]["ck"].shape[-1] // dh
        ck = jnp.einsum(
            "bsd,ldi->lbsi", enc_out, params["dec_stack"]["ck"].astype(cd)
        )
        cv = jnp.einsum(
            "bsd,ldi->lbsi", enc_out, params["dec_stack"]["cv"].astype(cd)
        )
        shape = (cfg.dec_layers, b, s_enc, H, dh)
        return (
            ck.reshape(shape).transpose(0, 1, 3, 2, 4),
            cv.reshape(shape).transpose(0, 1, 3, 2, 4),
        )

    def make_encode(self):
        """Jitted (params, enc_ids, enc_mask) -> (enc_out, fresh
        serving cache): the encoder scan and the per-layer cross-K/V
        projection compile into ONE program (generate's eager path
        would otherwise pay per-op dispatch for the whole encoder
        every call). `enc_mask` is a concrete [B, Senc] validity mask
        (all-ones when nothing is padded) so one compiled signature
        serves both cases."""
        from defer_tpu.utils.memo import cached_step

        def build():
            def fn(params, ids, mask):
                enc_out = self.encode(params, ids, mask=mask)
                return enc_out, self.start_cache(params, enc_out, mask)

            return jax.jit(fn)

        return cached_step(self, "encode", build)

    def prefill(
        self, params: dict, cache: dict, ids: jax.Array
    ) -> tuple[jax.Array, dict]:
        """Consume [B, T] decoder ids into the cache; returns
        (last_logits [B, V], cache). This is the GUARDED entry for
        multi-token steps: the jitted step cannot check the write
        head, and `lax.dynamic_update_slice` CLAMPS an out-of-range
        start — an unguarded overflow would silently overwrite live
        cache rows (same hazard gpt.py's prefill guards)."""
        # analysis: ignore[host-sync-in-hot-loop] one scalar sync per
        # prefill (admission time, not per tick) to guard overflow
        base = int(jax.device_get(cache["pos"]))
        t = ids.shape[1]
        if base + t > self.cfg.max_len:
            raise ValueError(
                f"cache position {base} + {t} tokens exceeds max_len "
                f"{self.cfg.max_len}"
            )
        logits, cache = self.make_step()(params, cache, ids)
        return logits[:, -1, :], cache

    def _step_fn(self, tp_axis: str | None = None):
        """The ONE incremental-step body shared by the single-device
        and tensor-parallel paths (gpt.py's convention): under tp each
        shard holds one head group (local-width splits, head-sliced
        rel-bias table, head-group caches) and psums close the wo/co/w2
        row-parallel matmuls; the embedding is vocab-row sharded."""
        cfg = self.cfg
        dh = cfg.head_dim

        def step(params, cache, ids):
            b, t = ids.shape
            H = params["dec_stack"]["wk"].shape[-1] // dh
            pos = cache["pos"]
            x = self._embed(params, ids, tp_axis)
            qpos = pos + jnp.arange(t)
            kpos = jnp.arange(cfg.max_len)
            self_bias = _rel_bias(
                params["dec_rel_bias"],
                qpos,
                kpos,
                bidirectional=False,
                num_buckets=cfg.rel_buckets,
                max_distance=cfg.rel_max_distance,
            )
            # Causal-by-position over the static cache: query at
            # absolute pos+i sees slot j iff j <= pos+i.
            mask = kpos[None, :] <= qpos[:, None]  # (T, S_max)
            self_bias = jnp.where(mask[None, None], self_bias, -jnp.inf)

            def split(t_flat):
                return t_flat.reshape(b, t, H, dh).transpose(0, 2, 1, 3)

            def block(carry, layer):
                x = carry
                p, kc, vc, ck, cv = layer
                dt = x.dtype
                h = self._rms(x, p["ln1_scale"])
                q = split(h @ p["wq"].astype(dt))
                k = split(h @ p["wk"].astype(dt))
                v = split(h @ p["wv"].astype(dt))
                kc = lax.dynamic_update_slice(kc, k, (0, 0, pos, 0))
                vc = lax.dynamic_update_slice(vc, v, (0, 0, pos, 0))
                # T5: NO 1/sqrt(dh) scaling on the logits.
                logits = jnp.einsum(
                    "bhtd,bhsd->bhts",
                    q,
                    kc,
                    preferred_element_type=jnp.float32,
                )
                logits = logits + self_bias
                w = jax.nn.softmax(logits, axis=-1).astype(dt)
                attn = jnp.einsum("bhts,bhsd->bhtd", w, vc)
                attn = attn.transpose(0, 2, 1, 3).reshape(b, t, H * dh)
                attn = attn @ p["wo"].astype(dt)
                if tp_axis is not None:
                    attn = lax.psum(attn, tp_axis)
                x = x + attn
                # Cross-attention against the precomputed encoder K/V;
                # cross_bias (baked at cache start) excludes pad
                # encoder keys, all real positions stay visible.
                h = self._rms(x, p["lnx_scale"])
                q = split(h @ p["cq"].astype(dt))
                logits = jnp.einsum(
                    "bhtd,bhsd->bhts",
                    q,
                    ck,
                    preferred_element_type=jnp.float32,
                )
                logits = logits + cache["cross_bias"]
                w = jax.nn.softmax(logits, axis=-1).astype(dt)
                cross = jnp.einsum("bhts,bhsd->bhtd", w, cv)
                cross = cross.transpose(0, 2, 1, 3).reshape(b, t, H * dh)
                cross = cross @ p["co"].astype(dt)
                if tp_axis is not None:
                    cross = lax.psum(cross, tp_axis)
                x = x + cross
                x = x + self._ffn(p, self._rms(x, p["ln2_scale"]), tp_axis)
                return x, (kc, vc)

            x, (new_k, new_v) = lax.scan(
                block,
                x,
                (
                    params["dec_stack"],
                    cache["k"],
                    cache["v"],
                    cache["cross_k"],
                    cache["cross_v"],
                ),
            )
            x = self._rms(x, params["dec_final_ln"])
            new_cache = {
                **cache,
                "k": new_k,
                "v": new_v,
                "pos": pos + t,
            }
            return self._head(params, x), new_cache

        return step

    def make_step(self, *, donate: bool = True):
        """Jitted (params, cache, ids [B, T]) -> (logits [B, T, V],
        cache): the incremental decode step (prefill T>=1 or decode
        T=1), static cache buffers, masks by cache position. The
        caller must keep pos + T <= max_len (use `prefill` for the
        guarded multi-token entry)."""
        from defer_tpu.utils.memo import cached_step

        return cached_step(
            self,
            ("step", donate),
            lambda: jax.jit(
                self._step_fn(), donate_argnums=(1,) if donate else ()
            ),
        )

    def generate(
        self,
        params: dict,
        enc_ids: jax.Array,
        num_steps: int,
        *,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        min_p: float = 0.0,
        rep_penalty: float = 1.0,
        eos_id: int | None = None,
        rng: jax.Array | None = None,
        enc_mask: jax.Array | None = None,
    ) -> jax.Array:
        """Encode once, then greedy/sampled decoding from the start
        token: [B, Senc] -> [B, 1 + num_steps] decoder ids (leading
        start token included). Pass `enc_mask` [B, Senc] (1 = real
        token) when the batch was padded to a common length."""
        cfg = self.cfg
        if num_steps + 1 > cfg.max_len:
            raise ValueError(
                f"{num_steps} steps + start token exceeds max_len "
                f"{cfg.max_len}"
            )
        b = enc_ids.shape[0]
        if enc_mask is None:
            enc_mask = jnp.ones(enc_ids.shape, jnp.int32)
        _, cache = self.make_encode()(params, enc_ids, enc_mask)
        step = self.make_step()
        ids = jnp.full((b, 1), cfg.decoder_start_token_id, jnp.int32)
        if rng is None:
            rng = jax.random.key(0)
        last, cache = self.prefill(params, cache, ids)
        return sampled_decode_loop(
            step,
            params,
            cache,
            last,
            ids,
            num_steps,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            min_p=min_p,
            rep_penalty=rep_penalty,
            eos_id=eos_id,
            rng=rng,
        )


@dataclasses.dataclass
class SpmdT5(T5):
    """Tensor-parallel T5 over a 'model' mesh axis: one head group per
    shard in BOTH stacks (self- and cross-attention caches hold local
    heads only, the rel-bias tables shard on their head axis so each
    group reads just its own biases), column/row-sharded FFNs with
    psum, and a Megatron vocab-row-sharded embedding / LM head (padded
    to a tp multiple) — every weight matrix read 1/tp per chip, the
    same contract as SpmdGptDecoder."""

    mesh: Any = None
    tp_axis: str = "model"

    def __post_init__(self):
        if self.mesh is None or self.tp_axis not in self.mesh.axis_names:
            raise ValueError(
                f"SpmdT5 needs a mesh with a {self.tp_axis!r} axis"
            )
        cfg = self.cfg
        tp = self.mesh.shape[self.tp_axis]
        if cfg.num_heads % tp or cfg.ffn_dim % tp:
            raise ValueError(
                f"num_heads={cfg.num_heads} and ffn_dim={cfg.ffn_dim} "
                f"must divide by tp={tp}"
            )
        self._vocab_padded = -(-cfg.vocab_size // tp) * tp

    def _specs(self) -> dict:
        from jax.sharding import PartitionSpec as P

        tp = self.tp_axis
        gated = self.cfg.ffn_style == "gated-gelu"

        def stack(cross: bool) -> dict:
            p = {
                "wq": P(None, None, tp),
                "wk": P(None, None, tp),
                "wv": P(None, None, tp),
                "wo": P(None, tp, None),
                "ln1_scale": P(None, None),
                "ln2_scale": P(None, None),
                "w1": P(None, None, tp),
                "w2": P(None, tp, None),
            }
            if gated:
                p["w3"] = P(None, None, tp)
            if cross:
                p.update(
                    {
                        "cq": P(None, None, tp),
                        "ck": P(None, None, tp),
                        "cv": P(None, None, tp),
                        "co": P(None, tp, None),
                        "lnx_scale": P(None, None),
                    }
                )
            return p

        specs = {
            "token_embedding": P(tp, None),
            "enc_stack": stack(False),
            "dec_stack": stack(True),
            # Head axis sharded: each group reads only its own biases.
            "enc_rel_bias": P(None, tp),
            "dec_rel_bias": P(None, tp),
            "enc_final_ln": P(None),
            "dec_final_ln": P(None),
        }
        if not self.cfg.tie_word_embeddings:
            specs["lm_head"] = P(tp, None)
        return specs

    def shard_params(self, params: dict) -> dict:
        """Place replicated-init params onto the mesh (vocab rows
        padded to a tp multiple; pad rows are zeros, masked out of
        lookups and sliced off the logits)."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        pad = self._vocab_padded - params["token_embedding"].shape[0]
        if pad:
            params = {
                **params,
                "token_embedding": jnp.pad(
                    params["token_embedding"], ((0, pad), (0, 0))
                ),
            }
            if "lm_head" in params:
                params["lm_head"] = jnp.pad(
                    params["lm_head"], ((0, pad), (0, 0))
                )
        return jax.device_put(
            params,
            jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s),
                self._specs(),
                is_leaf=lambda s: isinstance(s, P),
            ),
        )

    def _cache_spec(self) -> dict:
        from jax.sharding import PartitionSpec as P

        tp = self.tp_axis
        kv = P(None, None, tp, None, None)  # [L, B, H, S, Dh] heads/tp
        return {
            "k": kv,
            "v": kv,
            "cross_k": kv,
            "cross_v": kv,
            "cross_bias": P(None, None, None, None),  # replicated
            "pos": P(),
        }

    def make_encode(self):
        from defer_tpu.utils.memo import cached_step
        from jax.sharding import PartitionSpec as P

        def build():
            def fn(params, ids, mask):
                enc_out = self.encode(
                    params, ids, tp_axis=self.tp_axis, mask=mask
                )
                return enc_out, self.start_cache(params, enc_out, mask)

            return jax.jit(
                jax.shard_map(
                    fn,
                    mesh=self.mesh,
                    in_specs=(self._specs(), P(None, None), P(None, None)),
                    out_specs=(P(None, None, None), self._cache_spec()),
                )
            )

        return cached_step(self, "encode", build)

    def make_forward(self):
        """Jitted tensor-parallel teacher-forced forward:
        (params, enc_ids, dec_ids, enc_mask) -> [B, T, V] fp32 logits
        — the tp training/eval path (encode + decode_logits under one
        shard_map; the vocab-sharded logit slices concatenate on the
        way out and the pad rows are sliced off)."""
        from defer_tpu.utils.memo import cached_step
        from jax.sharding import PartitionSpec as P

        vocab = self.cfg.vocab_size

        def build():
            def fn(params, enc_ids, dec_ids, mask):
                enc_out = self.encode(
                    params, enc_ids, tp_axis=self.tp_axis, mask=mask
                )
                return self.decode_logits(
                    params,
                    enc_out,
                    dec_ids,
                    tp_axis=self.tp_axis,
                    enc_mask=mask,
                )

            smapped = jax.shard_map(
                fn,
                mesh=self.mesh,
                in_specs=(
                    self._specs(),
                    P(None, None),
                    P(None, None),
                    P(None, None),
                ),
                out_specs=P(None, None, self.tp_axis),
            )

            def forward(params, enc_ids, dec_ids, mask):
                return smapped(params, enc_ids, dec_ids, mask)[..., :vocab]

            return jax.jit(forward)

        return cached_step(self, "forward", build)

    def make_step(self, *, donate: bool = True):
        from defer_tpu.utils.memo import cached_step
        from jax.sharding import PartitionSpec as P

        vocab = self.cfg.vocab_size

        def build():
            smapped = jax.shard_map(
                self._step_fn(tp_axis=self.tp_axis),
                mesh=self.mesh,
                in_specs=(self._specs(), self._cache_spec(), P(None, None)),
                # Vocab-sharded logit slices concatenate on the way out.
                out_specs=(P(None, None, self.tp_axis), self._cache_spec()),
            )

            def step(params, cache, ids):
                logits, cache = smapped(params, cache, ids)
                # Drop the pad vocab rows (zeros — could win an argmax).
                return logits[..., :vocab], cache

            return jax.jit(step, donate_argnums=(1,) if donate else ())

        return cached_step(self, ("step", donate), build)

    def decode_logits(
        self,
        params: dict,
        enc_out: jax.Array,
        dec_ids: jax.Array,
        tp_axis: str | None = None,
        *,
        enc_mask: jax.Array | None = None,
    ) -> jax.Array:
        """Direct (tp_axis=None) calls on shard_params output run the
        same math under GSPMD, but the head is vocab-PADDED to a tp
        multiple — slice the zero pad columns off so cross-entropy
        shapes match and argmax can never emit a pad id. Per-shard
        calls (tp_axis set, inside make_forward's shard_map) return
        the local slice untouched."""
        out = super().decode_logits(
            params, enc_out, dec_ids, tp_axis, enc_mask=enc_mask
        )
        if tp_axis is None:
            out = out[..., : self.cfg.vocab_size]
        return out


def spmd_t5(
    mesh: Any,
    cfg: T5Config,
    *,
    compute_dtype: Any = jnp.bfloat16,
    tp_axis: str = "model",
) -> SpmdT5:
    """Tensor-parallel T5 serving (mirrors models/llama.spmd_llama)."""
    return SpmdT5(cfg, compute_dtype=compute_dtype, mesh=mesh, tp_axis=tp_axis)


def t5_config(name: str = "small", **overrides: Any) -> T5Config:
    """Named T5 shapes ("small", "base", "large") with overrides."""
    shapes = {
        "small": dict(num_layers=6, dim=512, num_heads=8, ffn_dim=2048),
        "base": dict(num_layers=12, dim=768, num_heads=12, ffn_dim=3072),
        "large": dict(
            num_layers=24, dim=1024, num_heads=16, ffn_dim=4096
        ),
    }
    if name not in shapes:
        raise KeyError(f"unknown t5 size {name!r}; have {sorted(shapes)}")
    kw: dict[str, Any] = dict(shapes[name])
    kw.update(overrides)
    return T5Config(**kw)


def tiny_t5(**overrides: Any) -> T5:
    """Small config for tests / CPU."""
    kw: dict[str, Any] = dict(
        num_layers=2,
        dim=32,
        num_heads=4,
        head_dim=8,
        ffn_dim=64,
        vocab_size=96,
        rel_buckets=8,
        rel_max_distance=20,
        max_len=32,
    )
    kw.update(overrides)
    return T5(T5Config(**kw), compute_dtype=jnp.float32)


def from_hf_state_dict(cfg: T5Config, state_dict: Mapping[str, Any]) -> dict:
    """Map a HuggingFace `T5ForConditionalGeneration.state_dict()` onto
    the T5 param pytree (torch Linear stores [out, in]; the stacks
    compute x @ W with [in, out], so projections transpose)."""

    from defer_tpu.models.transplant import tensor_to_numpy

    def t(name: str) -> np.ndarray:
        return tensor_to_numpy(state_dict[name])

    def attn(side: str, i: int, layer: int, which: str) -> np.ndarray:
        mod = "SelfAttention" if layer == 0 else "EncDecAttention"
        return t(f"{side}.block.{i}.layer.{layer}.{mod}.{which}.weight").T

    def ffn(side: str, i: int, layer: int, which: str) -> np.ndarray:
        return t(
            f"{side}.block.{i}.layer.{layer}.DenseReluDense.{which}.weight"
        ).T

    def ln(side: str, i: int, layer: int) -> np.ndarray:
        return t(f"{side}.block.{i}.layer.{layer}.layer_norm.weight")

    gated = cfg.ffn_style == "gated-gelu"
    wi = "wi_0" if gated else "wi"

    def stack(side: str, L: int, cross: bool) -> dict:
        ffn_layer = 2 if cross else 1
        p = {
            "wq": np.stack([attn(side, i, 0, "q") for i in range(L)]),
            "wk": np.stack([attn(side, i, 0, "k") for i in range(L)]),
            "wv": np.stack([attn(side, i, 0, "v") for i in range(L)]),
            "wo": np.stack([attn(side, i, 0, "o") for i in range(L)]),
            "ln1_scale": np.stack([ln(side, i, 0) for i in range(L)]),
            "ln2_scale": np.stack(
                [ln(side, i, ffn_layer) for i in range(L)]
            ),
            "w1": np.stack([ffn(side, i, ffn_layer, wi) for i in range(L)]),
            "w2": np.stack(
                [ffn(side, i, ffn_layer, "wo") for i in range(L)]
            ),
        }
        if gated:
            p["w3"] = np.stack(
                [ffn(side, i, ffn_layer, "wi_1") for i in range(L)]
            )
        if cross:
            p.update(
                {
                    "cq": np.stack(
                        [attn(side, i, 1, "q") for i in range(L)]
                    ),
                    "ck": np.stack(
                        [attn(side, i, 1, "k") for i in range(L)]
                    ),
                    "cv": np.stack(
                        [attn(side, i, 1, "v") for i in range(L)]
                    ),
                    "co": np.stack(
                        [attn(side, i, 1, "o") for i in range(L)]
                    ),
                    "lnx_scale": np.stack(
                        [ln(side, i, 1) for i in range(L)]
                    ),
                }
            )
        return {k: jnp.asarray(v) for k, v in p.items()}

    params = {
        "token_embedding": jnp.asarray(t("shared.weight")),
        "enc_stack": stack("encoder", cfg.num_layers, cross=False),
        "dec_stack": stack("decoder", cfg.dec_layers, cross=True),
        "enc_rel_bias": jnp.asarray(
            t(
                "encoder.block.0.layer.0.SelfAttention"
                ".relative_attention_bias.weight"
            )
        ),
        "dec_rel_bias": jnp.asarray(
            t(
                "decoder.block.0.layer.0.SelfAttention"
                ".relative_attention_bias.weight"
            )
        ),
        "enc_final_ln": jnp.asarray(t("encoder.final_layer_norm.weight")),
        "dec_final_ln": jnp.asarray(t("decoder.final_layer_norm.weight")),
    }
    if "lm_head.weight" in state_dict:
        head = t("lm_head.weight")
        if not np.array_equal(head, np.asarray(params["token_embedding"])):
            params["lm_head"] = jnp.asarray(head)
    # Tie mismatches are the one config error that would otherwise fail
    # SILENTLY: _head both picks the weight and applies the tied-only
    # dim**-0.5 scaling from cfg, so a checkpoint that disagrees with
    # cfg.tie_word_embeddings yields logits off by sqrt(dim).
    if cfg.tie_word_embeddings and "lm_head" in params:
        raise ValueError(
            "checkpoint carries a distinct lm_head but "
            "cfg.tie_word_embeddings=True — load it with a v1.1-style "
            "config (tie_word_embeddings=False)"
        )
    if not cfg.tie_word_embeddings and "lm_head" not in params:
        raise ValueError(
            "cfg.tie_word_embeddings=False but the checkpoint has no "
            "distinct lm_head — load it with tie_word_embeddings=True"
        )
    return params
