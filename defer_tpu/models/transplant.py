"""Weight transplant: external checkpoints -> GraphParams.

The reference ships weights as raw compressed arrays over sockets
(reference src/dispatcher.py:75-88, src/node.py:74-92) and relies on
Keras `set_weights` ordering (reference src/node.py:42). Here the
analogous machinery is a layout-aware importer: it walks the IR graph,
asks a `WeightSource` for each parameter, converts the source
framework's array layout to ours (NHWC activations / HWIO kernels — the
TPU-native layout), shape-checks, and returns a fresh GraphParams
pytree.

Two sources are built in:

  * `KerasWeights` — Keras-style `{layer_name: [arrays]}` in Keras's
    `get_weights()` ordering (conv kernels already HWIO, depthwise
    kernels (kh, kw, cin, mult)). `load_keras_h5` reads the dict out of
    a Keras `save_weights` HDF5 file.
  * `TorchStateDict` — a torch `state_dict` (conv kernels OIHW,
    linear (out, in), BN running stats), with a configurable node-name
    -> torch-prefix map.

`export_keras_weights` is the inverse (GraphParams -> Keras-layout
dict), giving a lossless round trip and an interop path back to the
reference's ecosystem.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from defer_tpu.graph.ir import Graph, GraphParams, OpNode
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)


class TransplantError(ValueError):
    pass


# --------------------------------------------------------------------------
# Layout conversion, per op kind
# --------------------------------------------------------------------------

# Keras get_weights() ordering per op kind; None entries are skipped
# (parameters our init chose not to create, e.g. a disabled bias).
_KERAS_ORDER: dict[str, tuple[str, ...]] = {
    "conv": ("kernel", "bias"),
    "depthwise_conv": ("kernel", "bias"),
    "separable_conv": ("dw_kernel", "pw_kernel", "bias"),
    "dense": ("kernel", "bias"),
    "batch_norm": ("scale", "bias", "mean", "var"),
    # Keras Normalization stores [adapt_mean, adapt_variance, count];
    # count is bookkeeping with no analogue here and is never requested.
    "normalization": ("mean", "var", "count"),
}

_TORCH_KEYS: dict[str, dict[str, str]] = {
    "conv": {"kernel": "weight", "bias": "bias"},
    "depthwise_conv": {"kernel": "weight", "bias": "bias"},
    "dense": {"kernel": "weight", "bias": "bias"},
    "batch_norm": {
        "scale": "weight",
        "bias": "bias",
        "mean": "running_mean",
        "var": "running_var",
    },
    "layer_norm": {"scale": "weight", "bias": "bias"},
    "embedding": {"table": "weight"},
    "pos_embedding": {"table": "weight"},
}


def _from_keras(op: str, param: str, value: np.ndarray) -> np.ndarray:
    if op == "separable_conv" and param == "dw_kernel":
        kh, kw = value.shape[:2]
        return value.reshape(kh, kw, 1, -1)
    if op == "depthwise_conv" and param == "kernel":
        # (kh, kw, cin, mult) -> (kh, kw, 1, cin*mult). C-order flatten
        # puts output channel c*mult + m exactly where XLA's
        # feature_group_count=cin grouping expects it.
        kh, kw = value.shape[:2]
        return value.reshape(kh, kw, 1, -1)
    return value


def _to_keras(op: str, param: str, value: np.ndarray, attrs) -> np.ndarray:
    if op == "separable_conv" and param == "dw_kernel":
        kh, kw, _, cm = value.shape
        mult = int(attrs.get("depth_multiplier", 1))
        return value.reshape(kh, kw, cm // mult, mult)
    if op == "depthwise_conv" and param == "kernel":
        kh, kw, _, cm = value.shape
        mult = int(attrs.get("depth_multiplier", 1))
        return value.reshape(kh, kw, cm // mult, mult)
    return value


def tensor_to_numpy(value: Any) -> np.ndarray:
    """Coerce a checkpoint tensor (torch.Tensor — incl. bfloat16, which
    `.numpy()` rejects — or anything array-like) to a numpy array,
    without importing torch. The ONE coercion for every checkpoint-
    interop path (CNN transplant, llama, t5)."""
    if hasattr(value, "detach"):  # torch.Tensor
        value = value.detach().cpu()
        try:
            value = value.numpy()
        except TypeError:  # bfloat16: widen, then convert
            value = value.float().numpy()
    return np.asarray(value)


def _from_torch(op: str, param: str, value: np.ndarray) -> np.ndarray:
    if param == "kernel":
        if op == "conv":
            return np.transpose(value, (2, 3, 1, 0))  # OIHW -> HWIO
        if op == "depthwise_conv":
            # (cin*mult, 1, kh, kw) -> (kh, kw, 1, cin*mult); torch
            # groups=cin ordering matches XLA's (both c*mult + m).
            return np.transpose(value, (2, 3, 1, 0))
        if op == "dense":
            return np.transpose(value, (1, 0))  # (out, in) -> (in, out)
    return value


# --------------------------------------------------------------------------
# Weight sources
# --------------------------------------------------------------------------


class WeightSource:
    """Protocol: yield converted arrays for a node, or None to skip."""

    def get(self, node: OpNode, param: str, shape: tuple[int, ...]):
        raise NotImplementedError

    def keys_used(self) -> set[str]:
        raise NotImplementedError

    def all_keys(self) -> set[str]:
        raise NotImplementedError


@dataclasses.dataclass
class KerasWeights(WeightSource):
    """Keras-style `{layer_name: [arrays in get_weights() order]}`.

    `name_map` translates IR node names to source layer names (identity
    by default — the zoo's node naming is already Keras-shaped).

    `bn_missing` names the BN param a three-array BatchNormalization
    list is missing: Keras drops gamma from the FRONT for scale=False
    (the Inception family's config) and beta from the middle for
    center=False, so the array count alone cannot disambiguate.
    """

    weights: Mapping[str, Sequence[np.ndarray]]
    name_map: Callable[[str], str] = staticmethod(lambda n: n)
    bn_missing: str = "scale"

    def __post_init__(self) -> None:
        self._used: set[str] = set()
        if self.bn_missing not in ("scale", "bias"):
            raise TransplantError(
                f"bn_missing must be 'scale' or 'bias', got {self.bn_missing!r}"
            )

    def _present(self, op: str, n_arrays: int) -> tuple[str, ...]:
        order = _KERAS_ORDER[op]
        if op == "batch_norm" and n_arrays < 4:
            # Keras get_weights order is [gamma?][beta?] mean var, with
            # gamma/beta independently omitted by scale=False /
            # center=False — not truncated from the end.
            if n_arrays == 2:
                return ("mean", "var")
            if n_arrays == 3:
                keep = tuple(p for p in order if p != self.bn_missing)
                return keep
        # Other ops only ever omit the trailing bias (use_bias=False).
        return order[:n_arrays]

    def get(self, node: OpNode, param: str, shape):
        key = self.name_map(node.name)
        if key not in self.weights:
            return None
        order = _KERAS_ORDER.get(node.op)
        if order is None or param not in order:
            raise TransplantError(
                f"no Keras layout rule for op {node.op!r} param {param!r} "
                f"(node {node.name!r})"
            )
        arrays = list(self.weights[key])
        present = self._present(node.op, len(arrays))
        if param not in present:
            return None
        self._used.add(key)
        return _from_keras(node.op, param, np.asarray(arrays[present.index(param)]))

    def keys_used(self) -> set[str]:
        return self._used

    def all_keys(self) -> set[str]:
        return set(self.weights)


@dataclasses.dataclass
class TorchStateDict(WeightSource):
    """A torch ``state_dict`` source.

    `name_map` translates an IR node name to the torch module prefix
    (e.g. ``"conv1_conv" -> "conv1"``); the per-parameter suffix
    (``weight`` / ``bias`` / ``running_mean`` / ...) is appended by op
    kind. Identity prefix map by default.
    """

    state_dict: Mapping[str, Any]
    name_map: Callable[[str], str] = staticmethod(lambda n: n)

    def __post_init__(self) -> None:
        self._used: set[str] = set()

    def get(self, node: OpNode, param: str, shape):
        keys = _TORCH_KEYS.get(node.op)
        if keys is None or param not in keys:
            # Unknown op kinds are simply not covered by this source;
            # strict transplant() reports the node as missing, and
            # strict=False keeps its initialized values.
            return None
        key = f"{self.name_map(node.name)}.{keys[param]}"
        if key not in self.state_dict:
            return None
        value = tensor_to_numpy(self.state_dict[key])
        self._used.add(key)
        return _from_torch(node.op, param, value)

    def keys_used(self) -> set[str]:
        return self._used

    def all_keys(self) -> set[str]:
        # num_batches_tracked is BN bookkeeping with no analogue here;
        # exclude it so the unused-keys diagnostic stays signal.
        return {
            k for k in self.state_dict
            if not k.endswith(".num_batches_tracked")
        }


# --------------------------------------------------------------------------
# Transplant / export
# --------------------------------------------------------------------------


def transplant(
    graph: Graph,
    params: GraphParams,
    source: WeightSource,
    *,
    strict: bool = True,
    dtype: Any | None = None,
) -> dict:
    """Return a copy of `params` with every array the source provides.

    strict=True (default) raises if any parameterized node gets nothing
    from the source — the failure mode the reference hits silently when
    `set_weights` ordering drifts (reference src/node.py:42).
    """
    out: dict = {}
    missing: list[str] = []
    for node in graph.nodes:
        node_params = params.get(node.name, {})
        if not node_params:
            out[node.name] = node_params
            continue
        loaded = {}
        got_any = False
        for pname, cur in node_params.items():
            value = source.get(node, pname, tuple(cur.shape))
            if value is None:
                loaded[pname] = cur
                continue
            if tuple(value.shape) != tuple(cur.shape):
                raise TransplantError(
                    f"shape mismatch for {node.name}.{pname}: checkpoint "
                    f"{tuple(value.shape)} vs model {tuple(cur.shape)}"
                )
            loaded[pname] = jnp.asarray(value, dtype or cur.dtype)
            got_any = True
        if not got_any:
            missing.append(node.name)
        out[node.name] = loaded
    if strict and missing:
        raise TransplantError(
            f"source provided no weights for {len(missing)} parameterized "
            f"nodes, e.g. {missing[:5]}; pass strict=False to keep their "
            "initialized values"
        )
    unused = source.all_keys() - source.keys_used()
    if unused:
        # Typo'd layer names silently strand checkpoint arrays — the
        # reference's set_weights path has no such diagnostic at all
        # (reference src/node.py:42).
        log.warning(
            "transplant: %d checkpoint keys unused, e.g. %s",
            len(unused),
            sorted(unused)[:5],
        )
    return out


def export_keras_weights(
    graph: Graph, params: GraphParams
) -> dict[str, list[np.ndarray]]:
    """GraphParams -> Keras-layout `{layer: [arrays]}` (round-trippable
    through KerasWeights, and loadable into a same-architecture Keras
    model via `set_weights` for interop with the reference)."""
    out: dict[str, list[np.ndarray]] = {}
    node_map = graph.node_map
    for name, node_params in params.items():
        if not node_params:
            continue
        node = node_map[name]
        order = _KERAS_ORDER.get(node.op)
        if order is None:
            raise TransplantError(
                f"no Keras layout rule for op {node.op!r} (node {name!r})"
            )
        out[name] = [
            _to_keras(node.op, p, np.asarray(node_params[p]), node.attrs)
            for p in order
            if p in node_params
        ]
    return out


def _to_snake_case(name: str) -> str:
    """Keras's class-name -> object-name rule (Conv2D -> conv2d,
    BatchNormalization -> batch_normalization, ReLU -> re_lu)."""
    import re

    name = re.sub(r"\W+", "", name)
    name = re.sub("(.)([A-Z][a-z]+)", r"\1_\2", name)
    return re.sub("([a-z])([A-Z])", r"\1_\2", name).lower()


def _keras3_group_names(model_json) -> dict[str, str]:
    """h5 group name -> real layer name for a Keras 3 `.weights.h5`.

    Keras 3 names each layer's h5 group by snake-cased class name with
    a per-class counter in model.layers order (NOT by `layer.name`);
    the model JSON's config.layers order reproduces that assignment.
    """
    import json as _json

    spec = (
        _json.loads(model_json) if isinstance(model_json, str) else model_json
    )
    layers = spec.get("config", {}).get("layers", [])
    counters: dict[str, int] = {}
    mapping: dict[str, str] = {}
    for layer in layers:
        cls = layer.get("class_name", "")
        name = layer.get("name") or layer.get("config", {}).get("name")
        base = _to_snake_case(cls)
        idx = counters.get(base, 0)
        counters[base] = idx + 1
        mapping[base if idx == 0 else f"{base}_{idx}"] = name
    return mapping


def load_keras_h5(
    path: str, model_json=None
) -> dict[str, list[np.ndarray]]:
    """Read a Keras `save_weights` HDF5 file into `{layer: [arrays]}`.

    Supports both on-disk layouts: the classic topological layout
    (`layer_names` / `weight_names` attrs) that TF1/2-era Keras — the
    reference's environment — writes, and the Keras 3 `.weights.h5`
    layout (`layers/<object_name>/vars/<i>` datasets in
    `layer.weights` order, which matches `get_weights()` ordering).
    Keras 3 group names are per-class counters, not layer names; pass
    the model's `to_json()` string as `model_json` to resolve them to
    real layer names (otherwise the raw object names are returned).
    """
    import h5py

    out: dict[str, list[np.ndarray]] = {}
    with h5py.File(path, "r") as f:
        if "layers" in f and "layer_names" not in f.attrs:
            # Keras 3 layout.
            resolve = (
                _keras3_group_names(model_json) if model_json is not None
                else {}
            )
            layers_group = f["layers"]
            for lname in layers_group:
                g = layers_group[lname]
                if "vars" not in g:
                    continue
                vars_group = g["vars"]
                arrays = [
                    np.asarray(vars_group[k])
                    for k in sorted(vars_group, key=int)
                ]
                if arrays:
                    out[resolve.get(lname, lname)] = arrays
            return out
        root = f["model_weights"] if "model_weights" in f else f
        layer_names = [
            n.decode() if isinstance(n, bytes) else n
            for n in root.attrs.get("layer_names", list(root.keys()))
        ]
        for lname in layer_names:
            g = root[lname]
            weight_names = [
                n.decode() if isinstance(n, bytes) else n
                for n in g.attrs.get("weight_names", [])
            ]
            arrays = [np.asarray(g[w]) for w in weight_names]
            if arrays:
                out[lname] = arrays
    return out


# --------------------------------------------------------------------------
# Draft construction: shrink a GPT target into a speculation draft
# --------------------------------------------------------------------------

# Per-axis slice spec for each stacked-block parameter (after the
# leading layer axis): "d" = model width, "f" = FFN width, "kv" = the
# KV projection width (kv_heads * Dh — NEVER sliced: the draft must
# keep the target's kv_heads so its proposals come from the same
# attention geometry the verifier scores).
_DRAFT_STACK_AXES: dict[str, tuple[str, ...]] = {
    "wq": ("d", "d"),
    "wk": ("d", "kv"),
    "wv": ("d", "kv"),
    "wo": ("d", "d"),
    "w1": ("d", "f"),
    "w2": ("f", "d"),
    "w3": ("d", "f"),
    "ln1_scale": ("d",),
    "ln2_scale": ("d",),
    "ln1_bias": ("d",),
    "ln2_bias": ("d",),
    "bq": ("d",),
    "bk": ("kv",),
    "bv": ("kv",),
    "bo": ("d",),
    "b1": ("f",),
    "b2": ("d",),
}


def draft_width_geometry(cfg, width: float) -> tuple[int, int, int]:
    """(num_heads', dim', ffn_dim') for a width-pruned draft of `cfg`.

    Head count rounds to the nearest multiple of kv_heads (floor 1x)
    so GQA grouping survives the prune; Dh is untouched, so rope
    frequencies and per-head shapes stay target-identical and dim'
    follows the head count. FFN width scales freely (floor 1)."""
    if not (0.0 < width <= 1.0):
        raise TransplantError(
            f"width={width}: draft width fraction must be in (0, 1]"
        )
    kv = cfg.kv_heads
    dh = cfg.dim // cfg.num_heads
    heads = kv * max(1, round(cfg.num_heads * width / kv))
    heads = min(heads, cfg.num_heads)
    ffn = max(1, round(cfg.ffn_dim * width))
    return heads, heads * dh, ffn


def make_draft(
    decoder,
    params: Mapping[str, Any],
    *,
    layers: int | None = None,
    width: float | None = None,
    dtype: Any = None,
):
    """Carve a small speculation draft out of a GPT target.

    Returns `(draft_decoder, draft_params)` where the draft is the
    target with the first `layers` blocks kept (layer truncation)
    and/or its query heads + FFN pruned to a `width` fraction
    (head/FFN slicing with the matching projection rows/columns
    re-stitched so the sliced tree is a valid transformer). Vocab,
    kv_heads, head dim, positions (learned table or rope base) and
    max_len are preserved — exactly the geometry `DraftLanes`
    validates against the target at server construction.

    `dtype="int8"` additionally routes the sliced tree through
    `models/quant.py::quantize_decoder_params` (weight-only symmetric
    int8 — the draft's HBM reads halve again); any other dtype casts
    float leaves (like `GptDecoder.cast_params`). The draft is an
    APPROXIMATION of the target — acceptance < 1 is the point; the
    verify forward keeps outputs token-identical regardless.
    """
    from defer_tpu.models.gpt import GptDecoder
    from defer_tpu.models.quant import quantize_decoder_params

    cfg = getattr(decoder, "cfg", None)
    if cfg is None or "stack" not in params:
        raise TransplantError(
            "make_draft needs a GptDecoder-style (decoder, params) pair "
            "(a .cfg config and a params['stack'] block tree)"
        )
    if any(
        isinstance(v, dict) and "q" in v
        for v in list(params["stack"].values())
        + [params.get("token_embedding")]
        if v is not None
    ):
        raise TransplantError(
            "make_draft slices float params: quantized {'q','s'} leaves "
            "would lose their per-channel scales — build the draft from "
            "the float tree, then ask for dtype='int8'"
        )
    L = cfg.num_layers
    keep_l = L if layers is None else layers
    if not (1 <= keep_l <= L):
        raise TransplantError(
            f"layers={layers}: draft must keep between 1 and "
            f"{L} (the target's depth) blocks"
        )
    if width is None:
        heads, dim, ffn = cfg.num_heads, cfg.dim, cfg.ffn_dim
    else:
        heads, dim, ffn = draft_width_geometry(cfg, width)
    dims = {"d": dim, "f": ffn, "kv": cfg.kv_heads * (cfg.dim // cfg.num_heads)}

    def cut(leaf, axes):
        idx = (slice(0, keep_l),) + tuple(
            slice(0, dims[a]) for a in axes
        )
        return jnp.asarray(leaf)[idx]

    stack = {}
    for k, v in params["stack"].items():
        if k not in _DRAFT_STACK_AXES:
            raise TransplantError(
                f"stack param {k!r} has no draft slice rule — drafts "
                "support plain GPT/llama decoder stacks (no MoE, no "
                "LoRA adapters; merge adapters first)"
            )
        stack[k] = cut(v, _DRAFT_STACK_AXES[k])
    out: dict[str, Any] = {"stack": stack}
    out["token_embedding"] = jnp.asarray(params["token_embedding"])[:, :dim]
    out["final_ln_scale"] = jnp.asarray(params["final_ln_scale"])[:dim]
    if "final_ln_bias" in params:
        out["final_ln_bias"] = jnp.asarray(params["final_ln_bias"])[:dim]
    if "pos_embedding" in params:
        out["pos_embedding"] = jnp.asarray(params["pos_embedding"])[:, :dim]
    if "lm_head" in params:
        out["lm_head"] = jnp.asarray(params["lm_head"])[:dim, :]

    dcfg = dataclasses.replace(
        cfg,
        num_layers=keep_l,
        num_heads=heads,
        num_kv_heads=cfg.kv_heads,
        dim=dim,
        ffn_dim=ffn,
    )
    draft = GptDecoder(dcfg, compute_dtype=decoder.compute_dtype)
    if dtype == "int8":
        out = quantize_decoder_params(out)
    elif dtype is not None:
        out = {
            k: jax.tree_util.tree_map(
                lambda a: a.astype(dtype)
                if jnp.issubdtype(a.dtype, jnp.floating)
                else a,
                v,
            )
            for k, v in out.items()
        }
    log.info(
        "draft: %d/%d layers, %d/%d heads, dim %d/%d, ffn %d/%d%s",
        keep_l, L, heads, cfg.num_heads, dim, cfg.dim, ffn, cfg.ffn_dim,
        " (int8)" if dtype == "int8" else "",
    )
    return draft, out
