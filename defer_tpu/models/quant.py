"""Weight-only int8 quantization for decode serving (w8a16).

Decode latency is weight-HBM-read bound (models/gpt.py): every matrix
is read once per token. bf16 storage halves fp32 traffic; symmetric
per-output-channel int8 halves it again, with activations (and the
matmul accumulation) staying bf16/fp32 — the standard TPU serving
recipe. XLA fuses the dequant (convert + scale) into the consuming
matmul, so HBM sees 1 byte/weight and VMEM does the widening.

The reference's analogous seam is its lossy wire codec (ZFP fixed
precision, reference src/dispatcher.py:89-92) — compression where the
bytes hurt; here the bytes that hurt are HBM reads, not sockets.

Representation: a quantized leaf is `{"q": int8[..., out], "s":
f32 broadcastable-to-q}` — per-output-channel scales, kept per layer
(L leading on both) for stacked matrices — a plain pytree so
`lax.scan` over stacked layers, jit donation and tree_map all keep
working untouched.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


#: stack matrices worth quantizing (biases/norm scales are tiny).
DEFAULT_KEYS = ("wq", "wk", "wv", "wo", "w1", "w2", "w3")


def quantize_symmetric(x, axis=None, *, keepdims: bool = False, xp=jnp):
    """THE symmetric-int8 convention, shared by weight leaves, the
    codec's SCHEME_Q8 wire frames and the paged KV pool: q =
    clip(round(x / s), -127, 127) with s = max|x| / 127 reduced over
    `axis` (None = per-tensor). Degenerate scales — all-zero input, or
    an amax so small that amax/127 underflows (or is flushed) to 0 —
    clamp to 1.0, so the tensor quantizes to zeros instead of clipped
    +/-127 garbage. Non-finite inputs are the caller's contract: the
    codec raises before calling; jitted pool writes never see them.

    `xp` selects the array namespace (jnp for device code, np for the
    host-side codec, which quantizes in fp64). Returns (q, s); with
    keepdims=False the scale drops the reduced axes."""
    xf = xp.asarray(x)
    if not xp.issubdtype(xf.dtype, xp.floating):
        xf = xf.astype(xp.float32)
    if xf.size:
        amax = xp.max(xp.abs(xf), axis=axis, keepdims=True)
    else:  # empty tensors (codec edge case): np.max would raise
        red = (
            tuple(range(xf.ndim))
            if axis is None
            else ((axis,) if isinstance(axis, int) else tuple(axis))
        )
        red = {a % xf.ndim for a in red}
        shape = tuple(
            1 if i in red else d for i, d in enumerate(xf.shape)
        )
        amax = xp.zeros(shape, xf.dtype)
    s = amax / 127.0
    s = xp.where(s > 0.0, s, xp.ones_like(s))
    q = xp.clip(xp.round(xf / s), -127, 127).astype(xp.int8)
    if not keepdims:
        s = xp.squeeze(s, axis=axis)
    return q, s


def dequantize_symmetric(q, s, dtype: Any = jnp.float32, *, xp=jnp):
    """Inverse of quantize_symmetric: widen q and fold the scale back
    in. `s` must be broadcastable to `q` (keepdims scales are; reduced
    ones need the caller to re-expand). The multiply happens in
    `dtype`, so the codec's fp64 round-trip and a bf16 pool read both
    route through the same two lines."""
    return xp.asarray(q).astype(dtype) * xp.asarray(s).astype(dtype)


def quantize_leaf(w: jax.Array) -> dict[str, jax.Array]:
    """Symmetric per-output-channel int8: q = round(w / s) with
    s = max|w| / 127 over the contraction axes. The scale keeps
    broadcastable (keepdims) shape, and layer-stacked [L, in, out]
    matrices get PER-LAYER channel scales with the L axis leading —
    so `lax.scan` over stacked params slices q and s together."""
    wf = jnp.asarray(w, jnp.float32)
    red = (
        tuple(range(1, wf.ndim - 1))
        if wf.ndim >= 3
        else tuple(range(wf.ndim - 1))
    )
    q, s = quantize_symmetric(wf, axis=red, keepdims=True)
    return {"q": q, "s": s.astype(jnp.float32)}


def dequantize_leaf(leaf: Any, dtype: Any) -> jax.Array:
    """Widen {"q","s"} back to `dtype`; pass plain arrays through
    (cast), so call sites handle mixed quantized/plain trees with one
    helper. Inside jit the convert+scale fuses into the consumer."""
    if isinstance(leaf, dict) and "q" in leaf:
        return dequantize_symmetric(leaf["q"], leaf["s"], dtype)
    return leaf.astype(dtype)


def quantize_decoder_params(
    params: dict, *, keys: tuple[str, ...] = DEFAULT_KEYS
) -> dict:
    """Quantize a GptDecoder/llama param tree for serving: the stack's
    matmul weights plus the embedding / untied head. Norm scales,
    biases and positions stay in their float dtype (tiny, and norm
    precision matters)."""
    out = dict(params)
    out["stack"] = {
        k: quantize_leaf(v) if k in keys else v
        for k, v in params["stack"].items()
    }
    out["token_embedding"] = quantize_leaf(params["token_embedding"])
    if "lm_head" in params:
        out["lm_head"] = quantize_leaf(params["lm_head"])
    return out


def quantization_error(w: jax.Array) -> float:
    """Max relative reconstruction error of quantize_leaf on `w` —
    diagnostics for tests and calibration sanity checks."""
    leaf = quantize_leaf(w)
    back = dequantize_leaf(leaf, jnp.float32)
    denom = jnp.maximum(jnp.max(jnp.abs(w)), 1e-12)
    return float(jnp.max(jnp.abs(back - jnp.asarray(w, jnp.float32))) / denom)
