"""Weight-only int8 quantization for decode serving (w8a16).

Decode latency is weight-HBM-read bound (models/gpt.py): every matrix
is read once per token. bf16 storage halves fp32 traffic; symmetric
per-output-channel int8 halves it again, with activations (and the
matmul accumulation) staying bf16/fp32 — the standard TPU serving
recipe. XLA fuses the dequant (convert + scale) into the consuming
matmul, so HBM sees 1 byte/weight and VMEM does the widening.

The reference's analogous seam is its lossy wire codec (ZFP fixed
precision, reference src/dispatcher.py:89-92) — compression where the
bytes hurt; here the bytes that hurt are HBM reads, not sockets.

Representation: a quantized leaf is `{"q": int8[..., out], "s":
f32 broadcastable-to-q}` — per-output-channel scales, kept per layer
(L leading on both) for stacked matrices — a plain pytree so
`lax.scan` over stacked layers, jit donation and tree_map all keep
working untouched.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


#: stack matrices worth quantizing (biases/norm scales are tiny).
DEFAULT_KEYS = ("wq", "wk", "wv", "wo", "w1", "w2", "w3")


def quantize_leaf(w: jax.Array) -> dict[str, jax.Array]:
    """Symmetric per-output-channel int8: q = round(w / s) with
    s = max|w| / 127 over the contraction axes. The scale keeps
    broadcastable (keepdims) shape, and layer-stacked [L, in, out]
    matrices get PER-LAYER channel scales with the L axis leading —
    so `lax.scan` over stacked params slices q and s together."""
    wf = jnp.asarray(w, jnp.float32)
    red = (
        tuple(range(1, wf.ndim - 1))
        if wf.ndim >= 3
        else tuple(range(wf.ndim - 1))
    )
    s = jnp.max(jnp.abs(wf), axis=red, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-12)
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s.astype(jnp.float32)}


def dequantize_leaf(leaf: Any, dtype: Any) -> jax.Array:
    """Widen {"q","s"} back to `dtype`; pass plain arrays through
    (cast), so call sites handle mixed quantized/plain trees with one
    helper. Inside jit the convert+scale fuses into the consumer."""
    if isinstance(leaf, dict) and "q" in leaf:
        return leaf["q"].astype(dtype) * leaf["s"].astype(dtype)
    return leaf.astype(dtype)


def quantize_decoder_params(
    params: dict, *, keys: tuple[str, ...] = DEFAULT_KEYS
) -> dict:
    """Quantize a GptDecoder/llama param tree for serving: the stack's
    matmul weights plus the embedding / untied head. Norm scales,
    biases and positions stay in their float dtype (tiny, and norm
    precision matters)."""
    out = dict(params)
    out["stack"] = {
        k: quantize_leaf(v) if k in keys else v
        for k, v in params["stack"].items()
    }
    out["token_embedding"] = quantize_leaf(params["token_embedding"])
    if "lm_head" in params:
        out["lm_head"] = quantize_leaf(params["lm_head"])
    return out


def quantization_error(w: jax.Array) -> float:
    """Max relative reconstruction error of quantize_leaf on `w` —
    diagnostics for tests and calibration sanity checks."""
    leaf = quantize_leaf(w)
    back = dequantize_leaf(leaf, jnp.float32)
    denom = jnp.maximum(jnp.max(jnp.abs(w)), 1e-12)
    return float(jnp.max(jnp.abs(back - jnp.asarray(w, jnp.float32))) / denom)
