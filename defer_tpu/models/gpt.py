"""GPT-style causal decoder with a KV cache — beyond-reference family.

The reference streams fixed-shape CNN inference; the modern serving
workload is autoregressive decoding, which is only fast if the K/V
projections of past tokens are cached instead of recomputed per step.
TPU-shaped design:

  * static cache buffers [L, B, H, S_max, Dh] updated in place with
    `lax.dynamic_update_slice` — no dynamic shapes, so the decode step
    compiles ONCE and every token reuses it;
  * one jitted step serves both PREFILL (T prompt tokens at once, MXU-
    friendly) and DECODE (T=1): same code path, two compiled shapes;
  * attention masks by cache position (j <= pos + t), so padding slots
    beyond the write head never contribute;
  * layers run under `lax.scan` over the stacked params + cache —
    one compiled block body regardless of depth;
  * reuses the shared pre-LN transformer stack parameters
    (`init_stack`), so checkpoints interchange with SpmdBert/SpmdVit
    stacks of the same config.

`generate` drives greedy/temperature sampling from a host loop with
donated cache buffers (the returned cache aliases the input's memory).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from defer_tpu.parallel.transformer_stack import (
    TransformerConfig,
    _layer_norm,
    _rms_norm,
    apply_rope,
    embed_lookup,
    init_stack,
    norm_apply,
)


def seen_tokens_mask(ids: jax.Array, vocab: int) -> jax.Array:
    """[B, V] presence mask of `ids` [B, T]. Build it ONCE from the
    prompt, then mark each emitted token with a single-element scatter
    — O(B) per step instead of re-scattering the whole growing
    sequence."""
    b = ids.shape[0]
    return (
        jnp.zeros((b, vocab), bool)
        .at[jnp.arange(b)[:, None], ids]
        .set(True)
    )


def repetition_penalty(
    logits: jax.Array, seen: jax.Array, penalty: float
) -> jax.Array:
    """Discourage already-emitted tokens (HF semantics: a positive
    logit divides by the penalty, a negative one multiplies — both
    push the score down for penalty > 1). `seen` is a [B, V] presence
    mask (seen_tokens_mask) or, for one-shot use, a [B, T] id array."""
    if penalty == 1.0:
        return logits
    if seen.dtype != jnp.bool_:
        seen = seen_tokens_mask(seen, logits.shape[-1])
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def truncate_logits(
    logits: jax.Array,
    *,
    top_k: int = 0,
    top_p: float = 1.0,
    min_p: float = 0.0,
) -> jax.Array:
    """Mask logits outside the sampling support to -inf.

    top_k > 0 keeps the k highest logits (ties at the k-th value all
    survive). top_p < 1 keeps the nucleus: tokens whose cumulative
    probability mass, accumulated in descending-probability order,
    is needed to first reach top_p (the top token always survives).
    min_p > 0 keeps tokens whose probability is at least min_p times
    the top token's probability — a confidence-scaled floor that
    adapts to how peaked the distribution is. All filters are
    static-shape (top_k / sort + cumsum / max), so the policy jits
    into the decode step without host round trips.
    """
    neg = jnp.finfo(logits.dtype).min
    if top_k and top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if min_p > 0.0:
        probs = jax.nn.softmax(logits, axis=-1)
        floor = min_p * jnp.max(probs, axis=-1, keepdims=True)
        logits = jnp.where(probs < floor, neg, logits)
    if top_p < 1.0:
        desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # A token stays iff the mass strictly before it is < top_p;
        # the cutoff is the smallest surviving logit. Column 0 is the
        # highest-probability token — pinned so even top_p <= 0 keeps
        # it (otherwise everything masks and sampling turns uniform).
        keep = (cum - probs) < top_p
        keep = keep.at[..., 0].set(True)
        cutoff = jnp.min(
            jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff, neg, logits)
    return logits


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling policy for the serving stack
    (runtime/decode_server.py, runtime/paged.py): the same knobs
    `generate` takes, plus the seed that makes a server slot reproduce
    the solo stream exactly. temperature 0 = greedy (filters unused).

    `constraint` names a server-registered constraint DFA
    (defer_tpu/constrain/; servers take `constraints={name: dfa}`):
    the slot's logits are masked to grammar-admissible tokens every
    tick, composing with any temperature/filter setting — including
    the temperature-0 greedy fast path, which stays greedy over the
    masked logits."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    min_p: float = 0.0
    seed: int = 0
    constraint: str | None = None

    def validate(self) -> None:
        if self.temperature < 0:
            raise ValueError(f"temperature {self.temperature} < 0")
        if self.top_k < 0:
            raise ValueError(f"top_k {self.top_k} < 0")
        if not 0 < self.top_p <= 1:
            raise ValueError(f"top_p {self.top_p} not in (0, 1]")
        if not 0 <= self.min_p <= 1:
            raise ValueError(f"min_p {self.min_p} not in [0, 1]")


def truncate_logits_batched(
    logits: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
    min_p: jax.Array,
) -> jax.Array:
    """truncate_logits with PER-ROW (B,) parameter vectors instead of
    static scalars — the jitted decode tick of the serving stack runs
    every slot's policy in one batched pass. Same filters in the same
    order; a disabled filter (top_k <= 0 or >= V, top_p >= 1,
    min_p <= 0) reduces to a neutral threshold that compares
    identically to the skipped branch, so each row's output is
    BIT-IDENTICAL to truncate_logits on that row with its static
    params (the serving parity contract)."""
    neg = jnp.finfo(logits.dtype).min
    v = logits.shape[-1]
    # top_k: threshold at the row's k-th highest value (ties survive,
    # as with lax.top_k); disabled rows threshold at -inf.
    desc = jnp.sort(logits, axis=-1)[..., ::-1]
    kth = jnp.take_along_axis(
        desc, (jnp.clip(top_k, 1, v) - 1)[:, None], axis=-1
    )
    kth = jnp.where(
        ((top_k > 0) & (top_k < v))[:, None], kth, -jnp.inf
    )
    logits = jnp.where(logits < kth, neg, logits)
    # min_p: confidence-scaled floor over the top_k-masked rows
    # (min_p = 0 -> floor 0, nothing masks).
    probs = jax.nn.softmax(logits, axis=-1)
    floor = min_p[:, None] * jnp.max(probs, axis=-1, keepdims=True)
    logits = jnp.where(probs < floor, neg, logits)
    # top_p: nucleus over the re-sorted masked rows; disabled rows get
    # a -inf cutoff (everything survives).
    desc2 = jnp.sort(logits, axis=-1)[..., ::-1]
    probs2 = jax.nn.softmax(desc2, axis=-1)
    cum = jnp.cumsum(probs2, axis=-1)
    keep = (cum - probs2) < top_p[:, None]
    keep = keep.at[..., 0].set(True)
    cutoff = jnp.min(
        jnp.where(keep, desc2, jnp.inf), axis=-1, keepdims=True
    )
    cutoff = jnp.where((top_p < 1.0)[:, None], cutoff, -jnp.inf)
    return jnp.where(logits < cutoff, neg, logits)


@jax.jit
def sample_token_batched(
    logits_last: jax.Array,
    keys: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
    min_p: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """sample_token with per-row (B,) policies and ONE PRNG key per
    row: each row splits its key exactly once per emitted token — the
    key schedule solo generate follows — and draws its categorical on
    the row's filtered logits, so a server slot seeded with
    jax.random.key(seed) reproduces `generate(..., rng=key(seed))`
    bit-for-bit. Greedy rows (temperature <= 0) take argmax of the raw
    logits; their key advances harmlessly (re-seeded at admission).
    Returns (tokens (B,), advanced keys (B,))."""
    pair = jax.vmap(lambda kk: jax.random.split(kk, 2))(keys)
    carry, sub = pair[:, 0], pair[:, 1]
    greedy = temperature <= 0
    safe_t = jnp.where(greedy, 1.0, temperature)
    filtered = truncate_logits_batched(
        logits_last / safe_t[:, None], top_k, top_p, min_p
    )
    sampled = jax.vmap(jax.random.categorical)(sub, filtered)
    toks = jnp.where(
        greedy, jnp.argmax(logits_last, axis=-1), sampled
    )
    return toks, carry


@jax.jit
def sample_token_batched_nosort(
    logits_last: jax.Array,
    keys: jax.Array,
    temperature: jax.Array,
    min_p: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """sample_token_batched for ticks where NO row enables top-k or
    top-p: both of truncate_logits_batched's full-vocab O(V log V)
    sorts exist only to find the kth/nucleus thresholds, and with the
    filters disabled those thresholds are -inf, making their masking
    `where`s bitwise identity. This variant drops the sorts and keeps
    every op the survivors see — temperature scale, the min_p
    floor (same softmax over the same scaled logits), the categorical
    on the same advanced key — so each row's token is BIT-IDENTICAL
    to sample_token_batched with top_k=0 / top_p=1 on that row, and
    the key state advances identically (servers can switch variants
    tick-by-tick). Dispatch is the caller's job: SlotSampler tracks
    per-slot policies on the host and routes here only when no active
    slot sorts."""
    pair = jax.vmap(lambda kk: jax.random.split(kk, 2))(keys)
    carry, sub = pair[:, 0], pair[:, 1]
    greedy = temperature <= 0
    safe_t = jnp.where(greedy, 1.0, temperature)
    logits = logits_last / safe_t[:, None]
    # min_p exactly as in truncate_logits_batched (the top_k where it
    # follows there is identity at kth = -inf).
    neg = jnp.finfo(logits.dtype).min
    probs = jax.nn.softmax(logits, axis=-1)
    floor = min_p[:, None] * jnp.max(probs, axis=-1, keepdims=True)
    filtered = jnp.where(probs < floor, neg, logits)
    sampled = jax.vmap(jax.random.categorical)(sub, filtered)
    toks = jnp.where(
        greedy, jnp.argmax(logits_last, axis=-1), sampled
    )
    return toks, carry


def _flash_decode_mode() -> str | None:
    """Which attention path the T=1 decode step takes: None (the XLA
    einsum — default off-TPU and on tunneled backends), "tpu" (the
    pallas flash-decode kernel, when the backend can run Mosaic), or
    "interpret" (DEFER_TPU_PALLAS_INTERPRET=1 — the kernel through the
    pallas interpreter, for CI parity tests off-TPU). Checked at trace
    time; set the env before building steps (compiled steps are
    memoized)."""
    import os

    if os.environ.get("DEFER_TPU_PALLAS_INTERPRET") == "1":
        return "interpret"
    from defer_tpu.ops.attention import _pallas_available

    return "tpu" if _pallas_available() else None


#: Host-sync cadence for eos early-stop polling: `finished.all()` is a
#: blocking device round trip, so the decode loops check it every K
#: tokens instead of every token — early stop costs at most K-1 wasted
#: ticks while the loop keeps its host run-ahead.
EOS_POLL_EVERY = 8


def apply_eos(
    nxt: jax.Array, finished: jax.Array, eos_id: int
) -> tuple[jax.Array, jax.Array]:
    """Shared stop-token step for every decode loop (generate, T5):
    pin already-finished rows to eos_id BEFORE updating the mask, so a
    pinned row keeps counting as finished and a row finishes ON its
    first eos emission. Returns (next_tokens [B, 1], finished [B])."""
    nxt = jnp.where(finished[:, None], eos_id, nxt)
    finished = finished | (nxt[:, 0] == eos_id)
    return nxt, finished


def sampled_decode_loop(
    step,
    params: dict,
    cache,
    last: jax.Array,
    ids: jax.Array,
    num_steps: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    min_p: float = 0.0,
    rep_penalty: float = 1.0,
    eos_id: int | None = None,
    stop_sequences=None,
    pad_id: int | None = None,
    rng: jax.Array | None = None,
) -> jax.Array:
    """The one host-side decode loop both decoder families drive
    (GptDecoder.generate, T5.generate): sample from `last`, append to
    `ids`, feed the compiled `step(params, cache, nxt)` — with the
    eos machinery (pin finished rows, poll-every-K early break, pad
    back to the [B, T + num_steps] shape contract) in a single place.
    The final sampled token needs no forward pass; its logits would
    never be used.

    `stop_sequences` — multi-token stops (runtime/stopping.py): a row
    whose GENERATED tail completes any sequence stops mid-budget, its
    output ending with the stop sequence; later positions pin to
    `pad_id` (defaults to eos_id, else 0). Suffix matching is
    host-side, so stop-sequence decoding costs one device->host token
    transfer per step (the eos-only path keeps its poll-every-K
    run-ahead)."""
    b = ids.shape[0]
    dtype = ids.dtype
    if rng is None:
        rng = jax.random.key(0)
    finished = jnp.zeros((b,), bool) if eos_id is not None else None
    matchers = None
    if stop_sequences:
        from defer_tpu.runtime.stopping import StopMatcher, normalize_stops

        seqs = normalize_stops(stop_sequences)
        matchers = [StopMatcher(seqs) for _ in range(b)]
        stopped = np.zeros((b,), bool)
    pad_tok = (
        pad_id
        if pad_id is not None
        else (eos_id if eos_id is not None else 0)
    )
    # Presence mask built once from the prompt; each emitted token is
    # a single-element scatter (not a re-scan of the whole sequence).
    seen = None
    steps_done = 0
    for i in range(num_steps):
        if rep_penalty != 1.0:
            if seen is None:
                seen = seen_tokens_mask(ids, last.shape[-1])
            last = repetition_penalty(last, seen, rep_penalty)
        nxt, rng = sample_token(
            last, rng, temperature, top_k=top_k, top_p=top_p, min_p=min_p
        )
        nxt = nxt[:, None].astype(dtype)
        if eos_id is not None:
            nxt, finished = apply_eos(nxt, finished, eos_id)
        if matchers is not None:
            if stopped.any():
                # Rows that already hit a stop sequence emit padding.
                nxt = jnp.where(
                    jnp.asarray(stopped)[:, None],
                    jnp.asarray(pad_tok, dtype),
                    nxt,
                )
            # analysis: ignore[host-sync-in-hot-loop] stop matching is
            # a host automaton: one batched [B] transfer per step is
            # the documented price of stop_sequences (this branch only
            # runs when they are set)
            host_nxt = np.asarray(nxt[:, 0])
            # The per-token host sync is already paid here, so the eos
            # mask is free every step — it guards the matchers (an
            # eos-finished row's pinned padding must never stop-match;
            # matching covers GENERATED tokens only) and breaks the
            # loop without waiting for the EOS_POLL_EVERY cadence.
            eos_done = (
                # analysis: ignore[host-sync-in-hot-loop] rides the
                # per-token sync already paid just above — see comment
                np.asarray(finished) if eos_id is not None else None
            )
            for r in range(b):
                if stopped[r] or (
                    eos_done is not None and eos_done[r]
                ):
                    continue
                if matchers[r].push(int(host_nxt[r])):
                    stopped[r] = True
        if seen is not None:
            seen = seen.at[jnp.arange(b), nxt[:, 0]].set(True)
        ids = jnp.concatenate([ids, nxt], axis=1)
        steps_done = i + 1
        # Early break: the stop path is host-synchronous every step;
        # the eos-only path keeps its poll-every-K run-ahead.
        if matchers is not None:
            done_rows = (
                stopped if eos_done is None else (stopped | eos_done)
            )
            if done_rows.all():
                break
        elif (
            eos_id is not None
            and (i + 1) % EOS_POLL_EVERY == 0
            and bool(finished.all())
        ):
            break
        if i + 1 < num_steps:
            logits, cache = step(params, cache, nxt)
            last = logits[:, -1, :]
    if steps_done < num_steps:
        pad = jnp.full(
            (b, num_steps - steps_done),
            eos_id if eos_id is not None and matchers is None else pad_tok,
            dtype,
        )
        ids = jnp.concatenate([ids, pad], axis=1)
    return ids


def sample_token(
    logits_last: jax.Array,
    rng: jax.Array,
    temperature: float,
    *,
    top_k: int = 0,
    top_p: float = 1.0,
    min_p: float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    """One sampling policy for every decode loop (generate, examples):
    greedy at temperature 0 (filters ignored), otherwise categorical
    over logits/temperature restricted by truncate_logits.
    Returns (token_ids, next_rng)."""
    if temperature > 0:
        rng, sub = jax.random.split(rng)
        logits = truncate_logits(
            logits_last / temperature,
            top_k=top_k,
            top_p=top_p,
            min_p=min_p,
        )
        tok = jax.random.categorical(sub, logits, axis=-1)
    else:
        tok = jnp.argmax(logits_last, axis=-1)
    return tok, rng


@dataclasses.dataclass
class GptDecoder:
    """Decoder-only transformer with weight-tied output head.

    rolling_cache=True (sliding-window models only, rotary positions):
    the KV cache holds cfg.window slots instead of cfg.max_len, each
    new row overwriting slot position%W — cache memory is bounded by
    the window and generation length becomes unbounded. Attention runs
    over [cache, current-step keys] with explicit absolute positions,
    so a multi-token (prefill) step never loses in-window keys to
    same-step overwrites."""

    cfg: TransformerConfig
    compute_dtype: Any = jnp.bfloat16
    rolling_cache: bool = False

    def __post_init__(self):
        if self.cfg.norm_style != "pre":
            raise ValueError(
                "GptDecoder uses pre-LN blocks: cfg.norm_style must be 'pre'"
            )
        if self.cfg.num_experts:
            raise ValueError("MoE decoder blocks are not supported here")
        if self.cfg.lora_rank:
            raise ValueError(
                "GptDecoder serves merged weights only: fold adapters "
                "with parallel.lora.merge_lora and build the decoder "
                "from a lora_rank=0 config (same serving cost, no "
                "adapter keys in the cacheable step)"
            )
        if self.rolling_cache and (
            self.cfg.window is None or self.cfg.pos_style != "rope"
        ):
            raise ValueError(
                "rolling_cache needs cfg.window (sliding-window "
                "attention) and pos_style='rope' (positions are "
                "unbounded, a learned table is not)"
            )

    # -- params / cache ---------------------------------------------------

    def init(self, rng: jax.Array) -> dict:
        cfg = self.cfg
        k_embed, k_stack, k_ln = jax.random.split(rng, 3)
        p = {
            "token_embedding": jax.random.normal(
                k_embed, (cfg.vocab_size, cfg.dim)
            )
            * 0.02,
            "final_ln_scale": jnp.ones((cfg.dim,)),
            "stack": init_stack(k_stack, cfg),
        }
        if cfg.pos_style == "learned":
            p["pos_embedding"] = (
                jax.random.normal(
                    jax.random.fold_in(k_embed, 1), (cfg.max_len, cfg.dim)
                )
                * 0.02
            )
        if cfg.norm_type == "layer":
            p["final_ln_bias"] = jnp.zeros((cfg.dim,))
        return p

    def cast_params(self, params: dict) -> dict:
        """Float params re-stored in compute_dtype — the serving
        configuration. Decode is weight-HBM-read bound, so fp32-stored
        params (init's default, kept for test precision) cost 2x the
        bandwidth of bf16 storage; the step's per-use astype then
        becomes a no-op."""
        return jax.tree_util.tree_map(
            lambda a: a.astype(self.compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating)
            else a,
            params,
        )

    def init_cache(self, batch: int) -> dict:
        cfg = self.cfg
        dh = cfg.dim // cfg.num_heads
        # GQA caches store KV heads only — the architecture's memory
        # win: cache bytes scale with kv_heads, not num_heads. Rolling
        # caches bound the slot count by the attention window instead
        # of max_len.
        slots = cfg.window if self.rolling_cache else cfg.max_len
        shape = (cfg.num_layers, batch, cfg.kv_heads, slots, dh)
        return {
            "k": jnp.zeros(shape, self.compute_dtype),
            "v": jnp.zeros(shape, self.compute_dtype),
            "pos": jnp.zeros((), jnp.int32),
        }

    # -- one step (prefill or decode) -------------------------------------

    def _split_heads(self, x: jax.Array) -> jax.Array:
        # Head count inferred from the actual width: under tensor
        # parallelism each shard sees D/tp == local_heads * Dh.
        b, t, d = x.shape
        dh = self.cfg.dim // self.cfg.num_heads
        return x.reshape(b, t, d // dh, dh).transpose(0, 2, 1, 3)

    def _proj_fns(self, p: dict, dt, adapter_ids=None):
        """The (bias, proj) closures every block stage shares —
        factored out so the paged block-native steps
        (runtime/paged.py) run the EXACT projection code `_block`
        runs, not a reimplementation."""
        from defer_tpu.models.quant import dequantize_leaf

        def W(name):
            # Plain bf16/fp32 matrices pass through; int8-quantized
            # leaves ({"q","s"}, models/quant.py) widen here and XLA
            # fuses the dequant into the matmul (HBM reads stay int8).
            return dequantize_leaf(p[name], dt)

        def bias(h, name):
            return h + p[name].astype(dt) if name in p else h

        def proj(h, name):
            """Base matmul plus, in multi-LoRA serving, each batch
            row's OWN adapter delta: the per-layer adapter banks
            ({name}:a [A, in, r] / {name}:b [A, r, out], pre-scaled —
            parallel/lora.py::stack_adapters) are gathered by the
            slot's adapter id, so one weight read serves every tenant
            and only the two skinny per-row einsums differ."""
            y = h @ W(name)
            a = p.get(f"{name}:a")
            if a is not None and adapter_ids is not None:
                a_sel = a[adapter_ids].astype(dt)  # [B, in, r]
                b_sel = p[f"{name}:b"][adapter_ids].astype(dt)
                low = jnp.einsum("btd,bdr->btr", h, a_sel)
                y = y + jnp.einsum("btr,bro->bto", low, b_sel)
            return y

        return bias, proj

    def _attn_qkv(self, p: dict, x, pos, adapter_ids=None):
        """ln1 + q/k/v projections (+rope at the step's absolute
        positions) + head split: everything a block does BEFORE the
        cache layout matters. Returns (q [B,Hq,T,Dh], k, v
        [B,Hkv,T,Dh]). Shared verbatim by `_block` and the paged
        block-native steps so their new K/V rows are bit-identical."""
        cfg = self.cfg
        dt = x.dtype
        dh = cfg.dim // cfg.num_heads
        per_slot = getattr(pos, "ndim", 0) == 1
        bias, proj = self._proj_fns(p, dt, adapter_ids)
        h = norm_apply(cfg, x, p, "ln1")
        qf = bias(proj(h, "wq"), "bq")
        kf = bias(proj(h, "wk"), "bk")
        vf = bias(proj(h, "wv"), "bv")
        if cfg.pos_style == "rope":
            steps_r = jnp.arange(qf.shape[1])
            positions = (
                pos[:, None] + steps_r[None] if per_slot else pos + steps_r
            )
            qf = apply_rope(qf, dh, positions, cfg.rope_theta)
            kf = apply_rope(kf, dh, positions, cfg.rope_theta)
        return (
            self._split_heads(qf),
            self._split_heads(kf),
            self._split_heads(vf),
        )

    def _attn_out(self, p: dict, x, attn, tp_axis=None, adapter_ids=None):
        """Everything a block does AFTER attention: wo projection
        (+psum under tp), residual, ln2, FFN. `attn` is the merged
        [B, T, Hq*Dh] attention output. Shared by `_block` and the
        paged block-native steps."""
        cfg = self.cfg
        bias, proj = self._proj_fns(p, x.dtype, adapter_ids)
        attn = proj(attn, "wo")
        if tp_axis is not None:
            attn = lax.psum(attn, tp_axis)
        attn = bias(attn, "bo")
        x = x + attn
        h2 = norm_apply(cfg, x, p, "ln2")
        if cfg.ffn_style == "swiglu":
            gate = jax.nn.silu(proj(h2, "w1"))
            ff = proj(gate * proj(h2, "w3"), "w2")
            if tp_axis is not None:
                ff = lax.psum(ff, tp_axis)
            return x + ff
        ff = bias(proj(h2, "w1"), "b1")
        ff = jax.nn.gelu(ff)
        ff = proj(ff, "w2")
        if tp_axis is not None:
            ff = lax.psum(ff, tp_axis)
        return bias(x + ff, "b2")

    def _block(
        self,
        p: dict,
        x,
        k_cache,
        v_cache,
        pos,
        tp_axis=None,
        adapter_ids=None,
    ):
        """One decoder block on [B, T, D] with cache update; returns
        (out, new_k, new_v). Under shard_map with tp_axis set, the
        projections arrive column-sharded (this shard's head group),
        the caches hold only local heads, and wo/w2 are row-sharded
        with psum — the Megatron pattern on the decode path.

        GQA attends grouped: q reshapes to [B, Hkv, G, T, Dh] against
        the [B, Hkv, S, Dh] cache, so the shared KV head is READ once
        per group instead of materialized G times — decode is KV-cache
        bandwidth bound, which is the whole point of GQA.

        `pos` is the cache write head: a scalar (all batch elements at
        the same depth — generate/prefill), or a (B,) vector when
        every slot sits at its own depth (continuous batching,
        runtime/decode_server.py); the branch is trace-time static.

        Dtype contract for callers that own their cache storage: the
        caches arrive here ALREADY in the block's compute dtype. The
        paged server's int8 pool (runtime/paged.py kv_dtype="int8")
        dequantizes at its gather and requantizes the returned new
        rows at its scatter, so this read path — and the new_k/new_v
        it hands back — is storage-dtype-agnostic by construction."""
        cfg = self.cfg
        dt = x.dtype
        dh = cfg.dim // cfg.num_heads
        per_slot = getattr(pos, "ndim", 0) == 1
        q, k, v = self._attn_qkv(p, x, pos, adapter_ids)
        b, h_q, t, _ = q.shape

        if self.rolling_cache:
            win = cfg.window
            if per_slot:
                # Continuous batching over rolling caches: each slot's
                # write lands at ITS OWN pos % win, and the in-place
                # mask vectorizes per slot. T=1 only — admission
                # prefills each request through the scalar path
                # (runtime/decode_server.py) before lane insertion.
                if t != 1:
                    raise NotImplementedError(
                        "per-slot rolling caches decode one token per "
                        "tick; prefill requests individually before "
                        "lane insertion"
                    )
                slots = pos % win  # (B,)
                rows_b = jnp.arange(b)
                k_cache = k_cache.at[rows_b, :, slots, :].set(k[:, :, 0, :])
                v_cache = v_cache.at[rows_b, :, slots, :].set(v[:, :, 0, :])
                k_att, v_att = k_cache, v_cache
                s_idx = jnp.arange(win)
                held = pos[:, None] - (
                    (pos[:, None] - s_idx[None, :]) % win
                )  # (B, win)
                # Broadcasts over the shared [b, hkv, g, t, s] logits.
                mask = (held >= 0)[:, None, None, None, :]
            elif t > win:
                raise ValueError(
                    f"a rolling-cache step takes at most window={win} "
                    f"tokens at once (got {t}); prefill with chunk<={win}"
                )
            if not per_slot:
                # New rows land at position % win (scatter; t <= win
                # so slot indices are unique).
                slots = (pos + jnp.arange(t)) % win
                s_idx = jnp.arange(win)
                if t == 1:
                    # Decode fast path: write first, attend the cache
                    # IN PLACE (no per-step concat copies of the whole
                    # window). After the write every slot holds the
                    # latest position <= pos congruent to it — always
                    # inside the window — so only never-written slots
                    # mask out.
                    k_cache = k_cache.at[:, :, slots, :].set(k)
                    v_cache = v_cache.at[:, :, slots, :].set(v)
                    k_att, v_att = k_cache, v_cache
                    held = pos - ((pos - s_idx) % win)  # (win,)
                    mask = (held >= 0)[None, :]  # (1, win)
                else:
                    # Multi-token (prefill) step: attend over [cache,
                    # this step's keys] with EXPLICIT absolute
                    # positions — same-step rows never overwrite keys
                    # a same-step query still needs. Slot s holds the
                    # latest position <= pos-1 congruent to s
                    # (negative = never written).
                    held = pos - 1 - ((pos - 1 - s_idx) % win)  # (win,)
                    k_att = jnp.concatenate([k_cache, k], axis=2)
                    v_att = jnp.concatenate([v_cache, v], axis=2)
                    kpos = jnp.concatenate([held, pos + jnp.arange(t)])
                    qpos = pos + jnp.arange(t)[:, None]  # (T, 1)
                    mask = (
                        (kpos[None, :] <= qpos)
                        & (kpos[None, :] > qpos - win)
                        & (kpos[None, :] >= 0)
                    )  # (T, win+T)
                    k_cache = k_cache.at[:, :, slots, :].set(k)
                    v_cache = v_cache.at[:, :, slots, :].set(v)
        else:
            # Write the T new K/V rows at the cache head.
            if per_slot:
                upd = jax.vmap(
                    lambda c, new, pb: lax.dynamic_update_slice(
                        c, new, (0, pb, 0)
                    )
                )
                k_cache = upd(k_cache, k, pos)
                v_cache = upd(v_cache, v, pos)
            else:
                k_cache = lax.dynamic_update_slice(
                    k_cache, k, (0, 0, pos, 0)
                )
                v_cache = lax.dynamic_update_slice(
                    v_cache, v, (0, 0, pos, 0)
                )
            k_att, v_att = k_cache, v_cache
            # Causal-by-position: query t (absolute pos+t) sees cache
            # slot j iff j <= pos + t; empty slots beyond the head are
            # excluded by the same test. A sliding window additionally
            # drops slots more than `window`-1 behind (Mistral-style).
            j = jnp.arange(k_att.shape[2])
            if per_slot:
                tt = pos[:, None] + jnp.arange(t)  # (B, T)
                mask = j[None, None, :] <= tt[:, :, None]  # (B, T, S)
                if cfg.window is not None:
                    mask &= j[None, None, :] > tt[:, :, None] - cfg.window
                mask = mask[:, None, None, :, :]
            else:
                tt = pos + jnp.arange(t)[:, None]  # (T, 1)
                mask = j[None, :] <= tt  # (T, S)
                if cfg.window is not None:
                    mask &= j[None, :] > tt - cfg.window

        from defer_tpu.ops.pallas_attention import _pick_block

        flash_mode = (
            _flash_decode_mode()
            if t == 1
            and not self.rolling_cache
            and _pick_block(k_att.shape[2], 256) >= 8
            else None
        )
        if flash_mode is not None:
            # Serving hot path: the pallas flash-decode kernel fuses
            # mask + online softmax + weighted sum over only the LIVE
            # cache rows (ops/pallas_attention.py::flash_decode);
            # position masking semantics match the einsum path (query
            # at pos attends j <= pos, window optional).
            from defer_tpu.ops.pallas_attention import flash_decode

            posv = pos if per_slot else jnp.broadcast_to(pos, (b,))
            attn = flash_decode(
                q[:, :, 0, :],
                k_att,
                v_att,
                posv,
                window=cfg.window,
                interpret=flash_mode == "interpret",
            )  # [B, Hq, Dh]
            attn = attn.astype(dt).reshape(b, t, h_q * dh)
        else:
            hkv = k_att.shape[1]
            qg = q.reshape(b, hkv, h_q // hkv, t, dh)
            logits = jnp.einsum(
                "bkgtd,bksd->bkgts",
                qg,
                k_att,
                preferred_element_type=jnp.float32,
            ) * (dh**-0.5)
            logits = jnp.where(mask, logits, -jnp.inf)
            weights = jax.nn.softmax(logits, axis=-1).astype(dt)
            attn = jnp.einsum("bkgts,bksd->bkgtd", weights, v_att)
            attn = attn.reshape(b, h_q, t, dh)
            attn = attn.transpose(0, 2, 1, 3).reshape(b, t, h_q * dh)
        out = self._attn_out(p, x, attn, tp_axis, adapter_ids)
        return out, k_cache, v_cache

    def _step_fn(self, tp_axis: str | None = None):
        """The ONE step body (embed -> scan over blocks -> final LN ->
        tied head) shared by the single-device and tensor-parallel
        paths; the tp variant adds psum inside _block, Megatron vocab
        sharding around the embedding/tied head, and a shard_map
        wrapper around this."""

        def step(params, cache, ids):
            t = ids.shape[1]
            pos = cache["pos"]
            # Multi-LoRA serving: the slot -> adapter assignment is
            # per-slot state and rides in the cache.
            adapter_ids = cache.get("adapter")
            x = self._embed_tokens(params, ids, pos, tp_axis)

            def body(carry, layer):
                x = carry
                p, kc, vc = layer
                out, kc, vc = self._block(
                    p, x, kc, vc, pos,
                    tp_axis=tp_axis, adapter_ids=adapter_ids,
                )
                return out, (kc, vc)

            x, (new_k, new_v) = lax.scan(
                body, x, (params["stack"], cache["k"], cache["v"])
            )
            logits = self._final_logits(params, x)
            new_cache = {"k": new_k, "v": new_v, "pos": pos + t}
            if adapter_ids is not None:
                new_cache["adapter"] = adapter_ids
            return logits, new_cache

        return step

    def _embed_tokens(self, params, ids, pos, tp_axis=None):
        """Token (+learned position) embedding for a step at write
        head `pos` (scalar, or (B,) per-slot depths — continuous
        batching gathers each element's own position rows)."""
        cfg = self.cfg
        cd = self.compute_dtype
        t = ids.shape[1]
        emb = embed_lookup(params["token_embedding"], ids, tp_axis)
        if cfg.pos_style == "rope":
            # Rotary positions enter inside each block's q/k.
            return emb.astype(cd)
        if getattr(pos, "ndim", 0) == 1:
            posv = jnp.take(
                params["pos_embedding"],
                pos[:, None] + jnp.arange(t),
                axis=0,
            )
            return (emb + posv).astype(cd)
        posv = lax.dynamic_slice_in_dim(
            params["pos_embedding"], pos, t, axis=0
        )
        return (emb + posv).astype(cd)

    def _final_logits(self, params, x):
        """Final norm + output head, fp32: tied to the embedding
        unless the checkpoint shipped a distinct lm_head (untied llama
        releases). Under tp each shard produces its vocab slice
        [B, T, Vpad/tp]; the caller's out_specs concatenate the slices
        into the global logits (no in-body collective, and shard_map's
        replication checking stays on)."""
        from defer_tpu.models.quant import dequantize_leaf

        cfg = self.cfg
        xf = x.astype(jnp.float32)
        if cfg.norm_type == "rms":
            xn = _rms_norm(xf, params["final_ln_scale"], cfg.layer_norm_eps)
        else:
            xn = _layer_norm(
                xf,
                params["final_ln_scale"],
                params["final_ln_bias"],
                cfg.layer_norm_eps,
            )
        head = params.get("lm_head", params["token_embedding"])
        head = dequantize_leaf(head, jnp.float32)
        return xn @ head.T

    def stage_params(self, params: dict, first: int, last: int) -> dict:
        """The param subtree one contiguous pipeline stage of layers
        [first, last) needs (runtime/paged.py pp_stages=): its slice
        of the stacked block params, plus the embedding tables when it
        holds layer 0 (`_embed_tokens` inputs) and the final norm +
        (tied) head when it holds the last layer (`_final_logits`
        inputs). Slices are views of the same device buffers until a
        stage placement copies them — the layer axis leads every stack
        leaf, so one tree_map covers float and quantized trees
        alike."""
        L = self.cfg.num_layers
        if not (0 <= first < last <= L):
            raise ValueError(
                f"stage layer range [{first}, {last}) out of bounds "
                f"for {L} layers"
            )
        out: dict = {
            "stack": jax.tree_util.tree_map(
                lambda a: a[first:last], params["stack"]
            )
        }
        if first == 0:
            out["token_embedding"] = params["token_embedding"]
            if "pos_embedding" in params:
                out["pos_embedding"] = params["pos_embedding"]
        if last == L:
            out["final_ln_scale"] = params["final_ln_scale"]
            if "final_ln_bias" in params:
                out["final_ln_bias"] = params["final_ln_bias"]
            if "lm_head" in params:
                out["lm_head"] = params["lm_head"]
            else:
                out["token_embedding"] = params["token_embedding"]
        return out

    def _memo_key(self, donate: bool):
        """Memo key for make_step; subclasses extend it when the
        compiled step depends on more than the donate flag."""
        return donate

    def _memoized(self, donate: bool, build):
        from defer_tpu.utils.memo import cached_step

        return cached_step(
            self,
            self._memo_key(donate),
            lambda: jax.jit(build(), donate_argnums=(1,) if donate else ()),
        )

    def make_step(self, *, donate: bool = True):
        """Jitted (params, cache, ids [B, T]) -> (logits [B, T, V],
        cache). With donate=True (default) the cache argument's buffers
        are reused in place — the serving configuration."""
        return self._memoized(donate, self._step_fn)

    def decode_step_fn(self):
        """The RAW (unjitted) single-step body `(params, cache, ids)
        -> (logits, cache)` — trace-compatible with `lax.scan`, so the
        serving layer can fuse `decode_window=K` decode sub-steps into
        one jitted window program (runtime/decode_server.py /
        runtime/paged.py) instead of dispatching make_step K times
        from the host. Identical math to make_step's body: a window of
        K applications is bit-identical to K host-dispatched ticks."""
        return self._step_fn()

    # -- generation --------------------------------------------------------

    def prefill(
        self,
        params: dict,
        cache: dict,
        ids: jax.Array,
        *,
        chunk: int | None = None,
    ) -> tuple[jax.Array, dict]:
        """Consume a [B, T] prompt into the cache; returns
        (last_logits [B, V], cache).

        chunk=None runs one T-length step. A chunk size processes the
        prompt in fixed-size pieces instead: peak activation memory is
        O(chunk x T) rather than O(T^2) for the attention logits, and
        ONE compiled shape serves any prompt length — short prompts
        and tail pieces are zero-padded to the chunk (padded rows sit
        beyond the advanced position, so they are never attended and
        later writes overwrite them). Works on a warm cache: all
        bounds are taken from the cache's actual write head."""
        t0 = ids.shape[1]
        if getattr(cache["pos"], "ndim", 0) != 0:
            raise ValueError(
                "prefill needs a scalar-position cache (per-slot "
                "caches admit through runtime/decode_server.py)"
            )
        # analysis: ignore[host-sync-in-hot-loop] one scalar sync per
        # prefill (admission time, not per tick) to guard overflow
        base = int(jax.device_get(cache["pos"]))
        if self.rolling_cache:
            # Rolling caches have no end to overflow — positions are
            # unbounded and slots recycle — but a single step is
            # capped at the window, so long prompts auto-chunk.
            if chunk is None and t0 > self.cfg.window:
                chunk = self.cfg.window
        elif base + t0 > self.cfg.max_len:
            raise ValueError(
                f"cache position {base} + prompt {t0} exceeds max_len "
                f"{self.cfg.max_len}"
            )
        step = self.make_step()
        if chunk is None:
            logits, cache = step(params, cache, ids)
            return logits[:, -1, :], cache
        if chunk < 1:
            raise ValueError(f"chunk={chunk} must be >= 1")
        last = None
        for start in range(0, t0, chunk):
            piece = ids[:, start : start + chunk]
            real = piece.shape[1]
            # Pad short/tail pieces to the fixed chunk shape — but
            # only when the padded write stays inside the cache:
            # dynamic_update_slice CLAMPS an out-of-range start, which
            # would silently shift the write over earlier rows. At the
            # boundary, feed the short piece as its own compiled shape.
            # Rolling caches never pad: a pad row would EVICT the live
            # slot at its position%W while the rewound mask still
            # credits that slot with the evicted row's position.
            if (
                real < chunk
                and not self.rolling_cache
                and base + start + chunk <= self.cfg.max_len
            ):
                piece = jnp.concatenate(
                    [
                        piece,
                        jnp.zeros((ids.shape[0], chunk - real), ids.dtype),
                    ],
                    axis=1,
                )
            logits, cache = step(params, cache, piece)
            last = logits[:, real - 1, :]
            if piece.shape[1] > real:
                # Rewind the write head past the padded rows.
                cache = {**cache, "pos": cache["pos"] - (chunk - real)}
        return last, cache

    def generate(
        self,
        params: dict,
        prompt_ids: jax.Array,
        num_steps: int,
        *,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        min_p: float = 0.0,
        rep_penalty: float = 1.0,
        eos_id: int | None = None,
        stop_sequences=None,
        pad_id: int | None = None,
        rng: jax.Array | None = None,
        prefill_chunk: int | None = None,
    ) -> jax.Array:
        """Greedy (temperature 0) or sampled continuation of
        `prompt_ids` [B, T0]; returns [B, T0 + num_steps]. Prefill runs
        the whole prompt in one step (or fixed `prefill_chunk` pieces
        for long prompts — see prefill); each new token reuses the
        compiled T=1 step with donated cache.

        With `eos_id` set, a sequence that emits it is FINISHED: its
        remaining positions are pinned to eos_id (the shape contract
        stays [B, T0 + num_steps]), and the host loop stops early once
        every sequence has finished — the serving-standard stop-token
        behavior without any dynamic shapes."""
        cfg = self.cfg
        b, t0 = prompt_ids.shape
        if self.rolling_cache:
            # No length bound (slots recycle); prefill itself
            # auto-chunks long prompts at the window.
            pass
        elif t0 + num_steps > cfg.max_len:
            raise ValueError(
                f"prompt {t0} + steps {num_steps} exceeds max_len "
                f"{cfg.max_len}"
            )
        step = self.make_step()
        cache = self.init_cache(b)
        last, cache = self.prefill(
            params, cache, prompt_ids, chunk=prefill_chunk
        )
        return sampled_decode_loop(
            step,
            params,
            cache,
            last,
            prompt_ids,
            num_steps,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            min_p=min_p,
            rep_penalty=rep_penalty,
            eos_id=eos_id,
            stop_sequences=stop_sequences,
            pad_id=pad_id,
            rng=rng,
        )

    # -- reference (no cache) ---------------------------------------------

    def reference_logits(self, params: dict, ids: jax.Array) -> jax.Array:
        """Full causal forward (fresh cache, whole sequence in one
        non-donating step) — the correctness oracle for incremental
        decoding. A rolling-cache decoder streams the sequence in
        window-sized pieces instead (a single step is capped at the
        window), collecting every position's logits."""
        cache = self.init_cache(ids.shape[0])
        step = self.make_step(donate=False)
        if not self.rolling_cache or ids.shape[1] <= self.cfg.window:
            logits, _ = step(params, cache, ids)
            return logits
        outs = []
        for start in range(0, ids.shape[1], self.cfg.window):
            logits, cache = step(
                params, cache, ids[:, start : start + self.cfg.window]
            )
            outs.append(logits)
        return jnp.concatenate(outs, axis=1)


@dataclasses.dataclass
class SpmdGptDecoder(GptDecoder):
    """Tensor-parallel KV-cache decoding: one jitted shard_map step
    over a 'model' mesh axis.

    Each shard holds its head group's column-sharded q/k/v projections
    and a cache of ONLY its local heads ([L, B, H/tp, S_max, Dh] per
    device); attention is collective-free, and the wo/w2 row-parallel
    matmuls psum over ICI; the embedding/tied head is vocab-row
    sharded (masked lookup + psum in, per-shard logits + all_gather
    out) — so EVERY weight matrix is read 1/tp per chip, which is
    what decode latency needs (weights, not activations, dominate
    decode HBM traffic)."""

    mesh: Any = None
    tp_axis: str = "model"

    def _memo_key(self, donate: bool):
        # The sharded step's in_specs depend on which param leaves are
        # int8 trees (set by shard_params) — key the memo on that too,
        # or a step built before shard_params would keep stale specs.
        return (
            donate,
            getattr(self, "_quantized_emb", False),
            getattr(self, "_quantized_keys", frozenset()),
        )
    # Optional batch sharding: set to a mesh axis name (e.g. "data")
    # to shard the cache/ids/logits batch dim over it — dp x tp
    # serving in one program.
    dp_axis: str | None = None

    def __post_init__(self):
        super().__post_init__()
        if self.mesh is None or self.tp_axis not in self.mesh.axis_names:
            raise ValueError(
                f"SpmdGptDecoder needs a mesh with a {self.tp_axis!r} axis"
            )
        if self.dp_axis is not None:
            if self.dp_axis not in self.mesh.axis_names:
                raise ValueError(
                    f"dp_axis {self.dp_axis!r} is not a mesh axis "
                    f"({self.mesh.axis_names})"
                )
            if self.dp_axis == self.tp_axis:
                raise ValueError(
                    f"dp_axis and tp_axis must differ (both "
                    f"{self.dp_axis!r})"
                )
        tp = self.mesh.shape[self.tp_axis]
        cfg = self.cfg
        if cfg.num_heads % tp or cfg.dim % tp or cfg.ffn_dim % tp:
            raise ValueError(
                f"heads={cfg.num_heads}, dim={cfg.dim}, "
                f"ffn_dim={cfg.ffn_dim} must all divide by tp={tp}"
            )
        if cfg.kv_heads % tp:
            raise ValueError(
                f"num_kv_heads={cfg.kv_heads} must divide by tp={tp} "
                "(each shard needs whole kv head groups)"
            )
        # Real vocab sizes (50257, 32000, ...) rarely divide by tp:
        # pad the sharded table instead of rejecting (padded rows are
        # zeros, masked out of lookups and sliced off the logits).
        self._vocab_padded = -(-cfg.vocab_size // tp) * tp

    def _specs(self):
        from defer_tpu.parallel.transformer_stack import stack_specs
        from jax.sharding import PartitionSpec as P

        tp = self.tp_axis
        stack = stack_specs(None, tp, cfg=self.cfg)
        emb_spec = P(tp, None)
        qkeys = getattr(self, "_quantized_keys", frozenset())
        if qkeys:

            def qwrap(spec: P) -> dict:
                # The scale is keepdims-shaped like q with middle axes
                # of size 1: shard only the leading (layer) and
                # trailing (channel) axes the way q does.
                n = len(spec)
                s_spec = (
                    P(spec[0], *([None] * (n - 2)), spec[-1])
                    if n >= 3
                    else P(None, spec[-1])
                )
                return {"q": spec, "s": s_spec}

            stack = {
                k: qwrap(v) if k in qkeys else v for k, v in stack.items()
            }
        if getattr(self, "_quantized_emb", False):
            # Vocab-sharded int8 table: rows over tp, per-channel
            # scales replicated (they span D, not vocab).
            emb_spec = {"q": P(tp, None), "s": P(None, None)}
        specs = {
            # Megatron vocab sharding: embedding rows over tp; the
            # tied head reuses the same shards.
            "token_embedding": emb_spec,
            "final_ln_scale": P(),
            "stack": stack,
        }
        if self.cfg.pos_style == "learned":
            specs["pos_embedding"] = P()
        if self.cfg.norm_type == "layer":
            specs["final_ln_bias"] = P()
        return specs

    def shard_params(self, params: dict) -> dict:
        """Place replicated-init params onto the mesh: column/row
        sharded stack, vocab-row sharded embedding/tied head (padded
        to a tp multiple), replicated norms/positions."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        if "lm_head" in params:
            raise NotImplementedError(
                "untied output heads are not supported under tensor "
                "parallelism yet — the single-device GptDecoder serves "
                "untied checkpoints"
            )
        # Weight-only int8 trees (models/quant.py) shard like their
        # float counterparts: q takes the weight's spec, the
        # per-channel scale replicates its size-1 axes. Record which
        # leaves are quantized BEFORE _specs/make_step so the step's
        # in_specs match the tree (and key the step memo on it).
        self._quantized_keys = frozenset(
            k
            for k, v in params["stack"].items()
            if isinstance(v, dict) and "q" in v
        )
        emb = params["token_embedding"]
        self._quantized_emb = isinstance(emb, dict) and "q" in emb
        rows = emb["q"] if self._quantized_emb else emb
        pad = self._vocab_padded - rows.shape[0]
        if pad:
            padded = jnp.pad(rows, ((0, pad), (0, 0)))
            params = {
                **params,
                "token_embedding": {"q": padded, "s": emb["s"]}
                if self._quantized_emb
                else padded,
            }
        return jax.device_put(
            params,
            jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s),
                self._specs(),
                is_leaf=lambda s: isinstance(s, P),
            ),
        )

    def _cache_spec(self):
        from jax.sharding import PartitionSpec as P

        tp, dp = self.tp_axis, self.dp_axis
        return {
            # Cache batch shards over dp (axis 1), heads over tp
            # (axis 2) of [L,B,H,S,Dh].
            "k": P(None, dp, tp, None, None),
            "v": P(None, dp, tp, None, None),
            "pos": P(),
        }

    def make_step(self, *, donate: bool = True):
        from jax.sharding import PartitionSpec as P

        from defer_tpu.utils.compat import shard_map

        vocab = self.cfg.vocab_size

        def build():
            cache_spec = self._cache_spec()
            dp = self.dp_axis
            smapped = shard_map(
                self._step_fn(tp_axis=self.tp_axis),
                self.mesh,
                in_specs=(self._specs(), cache_spec, P(dp, None)),
                # Logits stay vocab-sharded inside; shard_map itself
                # concatenates the [B/dp, T, Vpad/tp] slices.
                out_specs=(P(dp, None, self.tp_axis), cache_spec),
            )

            def step(params, cache, ids):
                logits, cache = smapped(params, cache, ids)
                # Drop the pad vocab rows (zeros from padded weights —
                # leaving them in could win an argmax).
                return logits[..., :vocab], cache

            return step

        return self._memoized(donate, build)

    def decode_step_fn(self):
        # Inheriting GptDecoder's raw body would silently drop the
        # shard_map wrapper (tp psums, vocab sharding) — the window
        # fusion would trace but compute garbage on a mesh. Servers
        # asked for decode_window > 1 call this at construction to
        # fail fast instead.
        raise NotImplementedError(
            "decode_window > 1 is not supported under shard_map "
            "tensor parallelism: the fused window step would bypass "
            "SpmdGptDecoder's sharded make_step — serve with "
            "decode_window=1"
        )

    def init_cache(self, batch: int) -> dict:
        from jax.sharding import NamedSharding

        cfg = self.cfg
        dh = cfg.dim // cfg.num_heads
        shape = (cfg.num_layers, batch, cfg.kv_heads, cfg.max_len, dh)
        spec = self._cache_spec()
        # Allocate DIRECTLY sharded: materializing the full replicated
        # cache on device 0 first would transiently need tp x the
        # per-device footprint — an OOM at serving scale.
        kv_sh = NamedSharding(self.mesh, spec["k"])
        return {
            "k": jnp.zeros(shape, self.compute_dtype, device=kv_sh),
            "v": jnp.zeros(shape, self.compute_dtype, device=kv_sh),
            "pos": jax.device_put(
                jnp.zeros((), jnp.int32),
                NamedSharding(self.mesh, spec["pos"]),
            ),
        }


def tiny_gpt(seq_len: int = 32) -> GptDecoder:
    """Small config for tests / CPU."""
    return GptDecoder(
        TransformerConfig(
            num_layers=4,
            dim=64,
            num_heads=4,
            ffn_dim=128,
            vocab_size=128,
            max_len=seq_len,
            norm_style="pre",
        ),
        compute_dtype=jnp.float32,
    )
