"""GPT-style causal decoder with a KV cache — beyond-reference family.

The reference streams fixed-shape CNN inference; the modern serving
workload is autoregressive decoding, which is only fast if the K/V
projections of past tokens are cached instead of recomputed per step.
TPU-shaped design:

  * static cache buffers [L, B, H, S_max, Dh] updated in place with
    `lax.dynamic_update_slice` — no dynamic shapes, so the decode step
    compiles ONCE and every token reuses it;
  * one jitted step serves both PREFILL (T prompt tokens at once, MXU-
    friendly) and DECODE (T=1): same code path, two compiled shapes;
  * attention masks by cache position (j <= pos + t), so padding slots
    beyond the write head never contribute;
  * layers run under `lax.scan` over the stacked params + cache —
    one compiled block body regardless of depth;
  * reuses the shared pre-LN transformer stack parameters
    (`init_stack`), so checkpoints interchange with SpmdBert/SpmdVit
    stacks of the same config.

`generate` drives greedy/temperature sampling from a host loop with
donated cache buffers (the returned cache aliases the input's memory).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from defer_tpu.parallel.transformer_stack import (
    TransformerConfig,
    _layer_norm,
    init_stack,
)


@dataclasses.dataclass
class GptDecoder:
    """Decoder-only transformer with weight-tied output head."""

    cfg: TransformerConfig
    compute_dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.cfg.norm_style != "pre":
            raise ValueError(
                "GptDecoder uses pre-LN blocks: cfg.norm_style must be 'pre'"
            )
        if self.cfg.num_experts:
            raise ValueError("MoE decoder blocks are not supported here")

    # -- params / cache ---------------------------------------------------

    def init(self, rng: jax.Array) -> dict:
        cfg = self.cfg
        k_embed, k_stack, k_ln = jax.random.split(rng, 3)
        return {
            "token_embedding": jax.random.normal(
                k_embed, (cfg.vocab_size, cfg.dim)
            )
            * 0.02,
            "pos_embedding": jax.random.normal(
                jax.random.fold_in(k_embed, 1), (cfg.max_len, cfg.dim)
            )
            * 0.02,
            "final_ln_scale": jnp.ones((cfg.dim,)),
            "final_ln_bias": jnp.zeros((cfg.dim,)),
            "stack": init_stack(k_stack, cfg),
        }

    def init_cache(self, batch: int) -> dict:
        cfg = self.cfg
        dh = cfg.dim // cfg.num_heads
        shape = (cfg.num_layers, batch, cfg.num_heads, cfg.max_len, dh)
        return {
            "k": jnp.zeros(shape, self.compute_dtype),
            "v": jnp.zeros(shape, self.compute_dtype),
            "pos": jnp.zeros((), jnp.int32),
        }

    # -- one step (prefill or decode) -------------------------------------

    def _split_heads(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        h = self.cfg.num_heads
        return x.reshape(b, t, h, d // h).transpose(0, 2, 1, 3)

    def _block(self, p: dict, x, k_cache, v_cache, pos):
        """One decoder block on [B, T, D] with cache update; returns
        (out, new_k, new_v)."""
        cfg = self.cfg
        dt = x.dtype
        h = _layer_norm(x, p["ln1_scale"], p["ln1_bias"], cfg.layer_norm_eps)
        q = self._split_heads(h @ p["wq"].astype(dt) + p["bq"].astype(dt))
        k = self._split_heads(h @ p["wk"].astype(dt) + p["bk"].astype(dt))
        v = self._split_heads(h @ p["wv"].astype(dt) + p["bv"].astype(dt))
        # Write the T new K/V rows at the cache head.
        k_cache = lax.dynamic_update_slice(k_cache, k, (0, 0, pos, 0))
        v_cache = lax.dynamic_update_slice(v_cache, v, (0, 0, pos, 0))

        t = q.shape[2]
        s_max = k_cache.shape[2]
        dh = q.shape[-1]
        logits = jnp.einsum(
            "bhtd,bhsd->bhts",
            q,
            k_cache,
            preferred_element_type=jnp.float32,
        ) * (dh**-0.5)
        # Causal-by-position: query t (absolute pos+t) sees cache slot
        # j iff j <= pos + t; empty slots beyond the head are excluded
        # by the same test.
        j = jnp.arange(s_max)[None, :]
        tt = pos + jnp.arange(t)[:, None]
        logits = jnp.where(j <= tt, logits, -jnp.inf)
        weights = jax.nn.softmax(logits, axis=-1).astype(dt)
        attn = jnp.einsum("bhts,bhsd->bhtd", weights, v_cache)
        b = attn.shape[0]
        attn = attn.transpose(0, 2, 1, 3).reshape(b, t, cfg.dim)
        attn = attn @ p["wo"].astype(dt) + p["bo"].astype(dt)
        x = x + attn
        h2 = _layer_norm(x, p["ln2_scale"], p["ln2_bias"], cfg.layer_norm_eps)
        ff = h2 @ p["w1"].astype(dt) + p["b1"].astype(dt)
        ff = jax.nn.gelu(ff)
        ff = ff @ p["w2"].astype(dt) + p["b2"].astype(dt)
        return x + ff, k_cache, v_cache

    def make_step(self, *, donate: bool = True):
        """Jitted (params, cache, ids [B, T]) -> (logits [B, T, V],
        cache). With donate=True (default) the cache argument's buffers
        are reused in place — the serving configuration. Memoized per
        donate flag: jit's cache is keyed on the function object, so a
        fresh closure per call would re-trace/re-compile every shape."""
        cached = getattr(self, "_steps", None)
        if cached is None:
            cached = self._steps = {}
        if donate in cached:
            return cached[donate]
        cfg = self.cfg
        cd = self.compute_dtype

        def step(params, cache, ids):
            b, t = ids.shape
            pos = cache["pos"]
            emb = jnp.take(params["token_embedding"], ids, axis=0)
            posv = lax.dynamic_slice_in_dim(
                params["pos_embedding"], pos, t, axis=0
            )
            x = (emb + posv).astype(cd)

            def body(carry, layer):
                x = carry
                p, kc, vc = layer
                out, kc, vc = self._block(p, x, kc, vc, pos)
                return out, (kc, vc)

            x, (new_k, new_v) = lax.scan(
                body, x, (params["stack"], cache["k"], cache["v"])
            )
            x = _layer_norm(
                x.astype(jnp.float32),
                params["final_ln_scale"],
                params["final_ln_bias"],
                cfg.layer_norm_eps,
            )
            logits = x @ params["token_embedding"].T  # tied head, fp32
            new_cache = {"k": new_k, "v": new_v, "pos": pos + t}
            return logits, new_cache

        fn = jax.jit(step, donate_argnums=(1,) if donate else ())
        cached[donate] = fn
        return fn

    # -- generation --------------------------------------------------------

    def generate(
        self,
        params: dict,
        prompt_ids: jax.Array,
        num_steps: int,
        *,
        temperature: float = 0.0,
        rng: jax.Array | None = None,
    ) -> jax.Array:
        """Greedy (temperature 0) or sampled continuation of
        `prompt_ids` [B, T0]; returns [B, T0 + num_steps]. Prefill runs
        the whole prompt in one step; each new token reuses the
        compiled T=1 step with donated cache."""
        cfg = self.cfg
        b, t0 = prompt_ids.shape
        if t0 + num_steps > cfg.max_len:
            raise ValueError(
                f"prompt {t0} + steps {num_steps} exceeds max_len "
                f"{cfg.max_len}"
            )
        step = self.make_step()
        cache = self.init_cache(b)
        logits, cache = step(params, cache, prompt_ids)
        ids = prompt_ids
        last = logits[:, -1, :]
        if rng is None:
            rng = jax.random.key(0)
        for i in range(num_steps):
            if temperature > 0:
                rng, sub = jax.random.split(rng)
                nxt = jax.random.categorical(sub, last / temperature, axis=-1)
            else:
                nxt = jnp.argmax(last, axis=-1)
            nxt = nxt[:, None].astype(prompt_ids.dtype)
            ids = jnp.concatenate([ids, nxt], axis=1)
            if i + 1 < num_steps:
                # The final sampled token needs no forward pass — its
                # logits would never be used.
                logits, cache = step(params, cache, nxt)
                last = logits[:, -1, :]
        return ids

    # -- reference (no cache) ---------------------------------------------

    def reference_logits(self, params: dict, ids: jax.Array) -> jax.Array:
        """Full causal forward (fresh cache, whole sequence in one
        non-donating step) — the correctness oracle for incremental
        decoding."""
        cache = self.init_cache(ids.shape[0])
        logits, _ = self.make_step(donate=False)(params, cache, ids)
        return logits


def tiny_gpt(seq_len: int = 32) -> GptDecoder:
    """Small config for tests / CPU."""
    return GptDecoder(
        TransformerConfig(
            num_layers=4,
            dim=64,
            num_heads=4,
            ffn_dim=128,
            vocab_size=128,
            max_len=seq_len,
            norm_style="pre",
        ),
        compute_dtype=jnp.float32,
    )
