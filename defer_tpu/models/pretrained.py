"""Pretrained-checkpoint loading for the model zoo.

The reference's drivers run real ImageNet weights end to end —
`ResNet50(weights='imagenet')` (reference src/local_infer.py:8) and the
same model shipped stage-by-stage to compute nodes (src/test.py:23).
This module is that capability for the native zoo: resolve a real Keras
checkpoint (a `save_weights` HDF5 file, either on-disk dialect, or
tf.keras.applications' own pretrained download/cache), then transplant
it into the zoo graph through `keras_name_map` + `load_keras_h5`.

Offline honesty: "imagenet" needs either a populated ~/.keras cache or
network; when neither exists `PretrainedUnavailable` is raised so
drivers can SKIP cleanly instead of half-running. "random" builds a
REAL tf.keras model with fresh weights — no network — which still
proves the full checkpoint->transplant->inference path numerically
(the TF model's own forward is returned for comparison).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any

from defer_tpu.models import Model, get_model
from defer_tpu.models.transplant import (
    KerasWeights,
    load_keras_h5,
    transplant,
)
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)


class PretrainedUnavailable(RuntimeError):
    """The requested checkpoint source cannot be produced here (no
    tensorflow, no network and no ~/.keras cache, ...)."""


def _tf_builder(name: str):
    import tensorflow as tf

    builders = {
        "resnet50": tf.keras.applications.ResNet50,
        "vgg16": tf.keras.applications.VGG16,
        "mobilenetv2": tf.keras.applications.MobileNetV2,
        "efficientnet_b0": tf.keras.applications.EfficientNetB0,
    }
    if name not in builders:
        raise PretrainedUnavailable(
            f"no tf.keras.applications builder wired for {name!r} "
            f"(have: {sorted(builders)})"
        )
    return builders[name]


def load_pretrained(
    name: str = "resnet50",
    weights: str = "imagenet",
    *,
    model_json: str | None = None,
    rng: Any = None,
) -> tuple[Model, dict, Any]:
    """Zoo model `name` + params transplanted from a real checkpoint.

    weights: an .h5/.weights.h5 path (Keras `save_weights`, either
    dialect), "imagenet" (tf.keras.applications pretrained — cache or
    download), or "random" (real tf.keras model, fresh weights, no
    network needed).

    Returns (model, params, tf_model); tf_model is the live Keras
    model when one was built (for output cross-checks), else None.

    Raises PretrainedUnavailable when the source cannot be produced —
    callers are expected to catch it and skip cleanly.
    """
    import jax

    model = get_model(name)
    if model.keras_name_map is None:
        raise PretrainedUnavailable(
            f"zoo model {name!r} has no keras_name_map"
        )

    tf_model = None
    if weights in ("imagenet", "random"):
        try:
            builder = _tf_builder(name)
        except ImportError as e:
            raise PretrainedUnavailable(
                f"tensorflow is not importable ({e})"
            ) from e
        try:
            tf_model = builder(
                weights="imagenet" if weights == "imagenet" else None
            )
        except Exception as e:  # noqa: BLE001 — download/cache failure
            raise PretrainedUnavailable(
                f"could not build {name}(weights={weights!r}): {e} — "
                "no network and no ~/.keras cache? Pass a local "
                ".h5 checkpoint path instead"
            ) from e
        fd, tmp = tempfile.mkstemp(suffix=".weights.h5")
        os.close(fd)
        try:
            tf_model.save_weights(tmp)
            layer_weights = load_keras_h5(tmp, tf_model.to_json())
        finally:
            os.unlink(tmp)
        src = f"tf.keras {name}({weights})"
    else:
        if not os.path.exists(weights):
            raise PretrainedUnavailable(
                f"checkpoint path {weights!r} does not exist"
            )
        # model_json may be the to_json() text or a path to it — the
        # Keras 3 .weights.h5 layout needs it to resolve per-class
        # counter group names to real layer names (load_keras_h5).
        if model_json is not None and os.path.exists(model_json):
            with open(model_json) as f:
                model_json = f.read()
        layer_weights = load_keras_h5(weights, model_json)
        src = weights

    # Init AFTER the cheap availability checks: every skip path above
    # must be near-free, not pay a full zoo-model init.
    base = model.init(rng if rng is not None else jax.random.key(0))
    params = transplant(
        model.graph,
        base,
        KerasWeights(layer_weights, name_map=model.keras_name_map),
        strict=True,
    )
    log.info("transplanted %s from %s", name, src)
    return model, params, tf_model
