"""DenseNet (121/169) in the graph IR — zoo extension.

Not in BASELINE.json's config list, but squarely in the reference's
capability envelope (`tf.keras.applications` family; its partitioner
claims any single-input single-output Keras DAG, reference
src/dag_util.py:29-33) — and a stress case the reference would
miscompile: the branch INSIDE each dense layer (BN-ReLU-conv-conv) runs
in parallel with the concat skip, so no node in it dominates the
downstream graph — only each block's concat output and the transition
layers are valid cuts. `cut_candidates` exposes exactly those; the
validated partitioner rejects anything else.

Node names follow real tf.keras DenseNet auto-naming
(`conv2_block1_1_conv`, `pool2_conv`, ...) so checkpoints and cut
lists written against Keras apply verbatim.
"""

from __future__ import annotations

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model

_EPS = 1.001e-5


def _dense_layer(b: GraphBuilder, x: str, growth: int, prefix: str) -> str:
    """BN-ReLU-1x1(4g) -> BN-ReLU-3x3(g), concatenated onto the input."""
    y = b.add("batch_norm", x, name=f"{prefix}_0_bn", eps=_EPS)
    y = b.add("relu", y, name=f"{prefix}_0_relu")
    y = b.add(
        "conv",
        y,
        name=f"{prefix}_1_conv",
        features=4 * growth,
        kernel_size=1,
        padding="VALID",
        use_bias=False,
    )
    y = b.add("batch_norm", y, name=f"{prefix}_1_bn", eps=_EPS)
    y = b.add("relu", y, name=f"{prefix}_1_relu")
    y = b.add(
        "conv",
        y,
        name=f"{prefix}_2_conv",
        features=growth,
        kernel_size=3,
        use_bias=False,
    )
    return b.add("concat", x, y, name=f"{prefix}_concat", axis=-1)


def _transition(b: GraphBuilder, x: str, features: int, prefix: str) -> str:
    x = b.add("batch_norm", x, name=f"{prefix}_bn", eps=_EPS)
    x = b.add("relu", x, name=f"{prefix}_relu")
    x = b.add(
        "conv",
        x,
        name=f"{prefix}_conv",
        features=features,
        kernel_size=1,
        padding="VALID",
        use_bias=False,
    )
    return b.add(
        "avg_pool", x, name=f"{prefix}_pool", window=2, strides=2,
        padding="VALID",
    )


def _build_densenet(
    name: str,
    blocks: tuple[int, ...],
    *,
    growth: int = 32,
    num_classes: int = 1000,
) -> Model:
    b = GraphBuilder(name)
    x = b.input("input")
    x = b.add("zero_pad", x, name="zero_padding2d", padding=((3, 3), (3, 3)))
    x = b.add(
        "conv",
        x,
        name="conv1_conv",
        features=64,
        kernel_size=7,
        strides=2,
        padding="VALID",
        use_bias=False,
    )
    x = b.add("batch_norm", x, name="conv1_bn", eps=_EPS)
    x = b.add("relu", x, name="conv1_relu")
    x = b.add(
        "zero_pad", x, name="zero_padding2d_1", padding=((1, 1), (1, 1))
    )
    x = b.add(
        "max_pool", x, name="pool1", window=3, strides=2, padding="VALID"
    )

    cuts: list[str] = []
    channels = 64
    for gi, num_layers in enumerate(blocks, start=2):
        for li in range(1, num_layers + 1):
            x = _dense_layer(b, x, growth, f"conv{gi}_block{li}")
            channels += growth
            # Each block's concat output dominates everything
            # downstream (later layers see earlier features only
            # through it) — a valid cut; the layer's internal branch
            # is not.
            cuts.append(x)
        if gi - 2 < len(blocks) - 1:
            channels //= 2
            x = _transition(b, x, channels, f"pool{gi}")
            cuts.append(x)

    x = b.add("batch_norm", x, name="bn", eps=_EPS)
    x = b.add("relu", x, name="relu")
    x = b.add("global_avg_pool", x, name="avg_pool")
    x = b.add("dense", x, name="predictions", features=num_classes)
    x = b.add("softmax", x, name="predictions_softmax")
    return Model(
        name=name,
        graph=b.build(x),
        input_shape=(224, 224, 3),
        cut_candidates=tuple(cuts),
    )


@register_model("densenet121")
def densenet121() -> Model:
    return _build_densenet("densenet121", (6, 12, 24, 16))


@register_model("densenet169")
def densenet169() -> Model:
    return _build_densenet("densenet169", (6, 12, 32, 32))
