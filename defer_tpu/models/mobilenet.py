"""MobileNetV2 — depthwise-separable edge model (BASELINE.json:
"MobileNetV2 / EfficientNet-B0 (depthwise-conv edge models)").

The reference's partitioner is model-generic over any single-in/single-out
Keras DAG (reference src/dag_util.py:29-33); MobileNetV2 is in its target
zoo via BASELINE.json. Built natively here as an IR graph with
Keras-compatible block naming (`block_3_add`, ...), so reference-style
cut lists apply unchanged.

Every inverted-residual block output is a single-tensor articulation
point: blocks chain linearly and the residual skip stays inside one
block, so all block outputs are valid cuts (SURVEY.md §3.4).
"""

from __future__ import annotations

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model


def _keras_name(node: str) -> str:
    """Native node name -> real tf.keras MobileNetV2 layer name (the
    names `MobileNetV2(weights='imagenet')` checkpoints use): the stem
    pair is `Conv1`/`bn_Conv1`, block convs drop the `_conv` suffix,
    and block BNs use an upper-case `_BN` suffix."""
    if node == "Conv1_conv":
        return "Conv1"
    if node == "Conv1_bn":
        return "bn_Conv1"
    if node == "Conv_1_conv":
        return "Conv_1"
    if node == "predictions_dense":
        return "predictions"
    for stem in ("_expand", "_project"):
        if node.endswith(f"{stem}_conv"):
            return node[: -len("_conv")]
        if node.endswith(f"{stem}_bn"):
            return node[: -len("_bn")] + "_BN"
    if node.endswith("_depthwise_bn"):
        return node[: -len("_bn")] + "_BN"
    return node


def _make_divisible(v: float, divisor: int = 8) -> int:
    """Channel rounding used by the MobileNet family (nearest multiple
    of 8, never dropping more than 10%)."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _conv_bn(
    b: GraphBuilder,
    x: str,
    features: int,
    kernel: int,
    *,
    strides: int = 1,
    relu6: bool = True,
    prefix: str,
) -> str:
    x = b.add(
        "conv",
        x,
        name=f"{prefix}_conv",
        features=features,
        kernel_size=kernel,
        strides=strides,
        padding="SAME",
        use_bias=False,
    )
    x = b.add("batch_norm", x, name=f"{prefix}_bn", eps=1e-3)
    if relu6:
        x = b.add("relu6", x, name=f"{prefix}_relu")
    return x


def _inverted_residual(
    b: GraphBuilder,
    x: str,
    in_ch: int,
    out_ch: int,
    *,
    stride: int,
    expansion: int,
    block_id: int,
) -> tuple[str, int]:
    """Expand(1x1) -> depthwise(3x3) -> project(1x1, linear) + skip."""
    prefix = f"block_{block_id}" if block_id > 0 else "expanded_conv"
    y = x
    if expansion != 1:
        y = _conv_bn(b, y, in_ch * expansion, 1, prefix=f"{prefix}_expand")
    y = b.add(
        "depthwise_conv",
        y,
        name=f"{prefix}_depthwise",
        kernel_size=3,
        strides=stride,
        padding="SAME",
        use_bias=False,
    )
    y = b.add("batch_norm", y, name=f"{prefix}_depthwise_bn", eps=1e-3)
    y = b.add("relu6", y, name=f"{prefix}_depthwise_relu")
    y = _conv_bn(b, y, out_ch, 1, relu6=False, prefix=f"{prefix}_project")
    if stride == 1 and in_ch == out_ch:
        y = b.add("add", x, y, name=f"{prefix}_add")
    return y, out_ch


# (expansion, out_channels, repeats, first-block stride) per group —
# the standard V2 schedule.
_V2_SCHEDULE = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


@register_model("mobilenetv2")
def mobilenetv2(num_classes: int = 1000, alpha: float = 1.0) -> Model:
    b = GraphBuilder("mobilenetv2")
    x = b.input("input")
    ch = _make_divisible(32 * alpha)
    x = _conv_bn(b, x, ch, 3, strides=2, prefix="Conv1")

    cuts: list[str] = []
    block_id = 0
    for expansion, out_base, repeats, stride in _V2_SCHEDULE:
        out_ch = _make_divisible(out_base * alpha)
        for i in range(repeats):
            x, ch = _inverted_residual(
                b,
                x,
                ch,
                out_ch,
                stride=stride if i == 0 else 1,
                expansion=expansion,
                block_id=block_id,
            )
            cuts.append(x)
            block_id += 1

    head = _make_divisible(1280 * alpha) if alpha > 1.0 else 1280
    x = _conv_bn(b, x, head, 1, prefix="Conv_1")
    cuts.append(x)
    x = b.add("global_avg_pool", x, name="global_average_pooling2d")
    x = b.add("dense", x, name="predictions_dense", features=num_classes)
    x = b.add("softmax", x, name="predictions")
    return Model(
        name="mobilenetv2",
        graph=b.build(x),
        input_shape=(224, 224, 3),
        cut_candidates=tuple(cuts),
        keras_name_map=_keras_name,
    )
