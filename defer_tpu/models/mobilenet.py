"""mobilenet — implemented in a later milestone this round."""
