"""NASNet (Mobile / Large) — the zoo's stress test for the partitioner
(BASELINE.json: "InceptionResNetV2 / NASNet (multi-branch DAG — stresses
dag_util partitioner)").

NASNet's cell i consumes BOTH cell i-1's and cell i-2's outputs (the
`p` skip), so cell boundaries are NOT single-tensor articulation points:
an edge from cell i-2 always crosses a cut placed after cell i-1. The
reference's unvalidated traversal (reference src/dag_util.py:11-27)
would silently duplicate whole cell subgraphs if cut there — and its
one-activation-per-hop wire protocol couldn't ship the pair anyway
(reference src/node.py:125-133). Here `cut_candidates` uses
multi-tensor boundaries (defer_tpu/graph/partition.py): the bundle
(cell_i, cell_{i-1}) jointly separates the chain at every cell, making
NASNet fully pipelinable; the stem conv output and the final-cell
concat (whose `p` companion is dropped before the head) stay
single-tensor.

Separable convs are composed from first-class `depthwise_conv` +
pointwise `conv` ops (Keras's SeparableConv2D fused pair). Strided
ops use SAME padding, which reproduces Keras's correct_pad+VALID pixel
alignment for all kernel/input parities used here.
"""

from __future__ import annotations

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model


def _sep_conv_block(
    b: GraphBuilder,
    x: str,
    filters: int,
    kernel: int,
    *,
    strides: int = 1,
    prefix: str,
) -> str:
    """relu -> sepconv(s) -> BN -> relu -> sepconv(1) -> BN."""
    x = b.add("relu", x, name=f"{prefix}_relu1")
    for i, s in enumerate((strides, 1), start=1):
        x = b.add(
            "depthwise_conv",
            x,
            name=f"{prefix}_sep{i}_dw",
            kernel_size=kernel,
            strides=s,
            padding="SAME",
            use_bias=False,
        )
        x = b.add(
            "conv",
            x,
            name=f"{prefix}_sep{i}_pw",
            features=filters,
            kernel_size=1,
            use_bias=False,
        )
        x = b.add("batch_norm", x, name=f"{prefix}_sep{i}_bn", eps=1e-3)
        if i == 1:
            x = b.add("relu", x, name=f"{prefix}_relu2")
    return x


def _fit_reduce(b: GraphBuilder, p: str, filters: int, *, prefix: str) -> str:
    """Halve p's spatial dims with the two shifted avg-pool paths
    (factorized reduction), then 1x1-project each half and concat."""
    p = b.add("relu", p, name=f"{prefix}_relu")
    p1 = b.add(
        "avg_pool", p, name=f"{prefix}_pool1", window=1, strides=2,
        padding="VALID",
    )
    p1 = b.add(
        "conv", p1, name=f"{prefix}_conv1", features=filters // 2,
        kernel_size=1, use_bias=False,
    )
    # Second path samples the grid offset by one pixel: pad bottom/right,
    # crop top/left, then the same stride-2 1x1 pool.
    p2 = b.add("zero_pad", p, name=f"{prefix}_pad", padding=((0, 1), (0, 1)))
    p2 = b.add("crop", p2, name=f"{prefix}_crop", cropping=((1, 0), (1, 0)))
    p2 = b.add(
        "avg_pool", p2, name=f"{prefix}_pool2", window=1, strides=2,
        padding="VALID",
    )
    # Both halves get filters//2 (mirroring the canonical factorized
    # reduction); for odd filters the adjusted tensor has filters-1
    # channels, which is fine — reduction cells only consume it through
    # re-projecting separable convs.
    p2 = b.add(
        "conv", p2, name=f"{prefix}_conv2", features=filters // 2,
        kernel_size=1, use_bias=False,
    )
    p = b.add("concat", p1, p2, name=f"{prefix}_concat")
    return b.add("batch_norm", p, name=f"{prefix}_bn", eps=1e-3)


def _adjust(
    b: GraphBuilder,
    p: str | None,
    ip: str,
    filters: int,
    *,
    p_stride_mismatch: bool,
    p_channels: int,
    prefix: str,
) -> str:
    """Shape p (cell i-2 output) to match ip's spatial dims / channels."""
    if p is None:
        return ip
    if p_stride_mismatch:
        return _fit_reduce(b, p, filters, prefix=f"{prefix}_adjust")
    if p_channels != filters:
        p = b.add("relu", p, name=f"{prefix}_adjust_relu")
        p = b.add(
            "conv", p, name=f"{prefix}_adjust_conv", features=filters,
            kernel_size=1, use_bias=False,
        )
        return b.add("batch_norm", p, name=f"{prefix}_adjust_bn", eps=1e-3)
    return p


def _squeeze(b: GraphBuilder, x: str, filters: int, *, prefix: str) -> str:
    """relu -> 1x1 conv -> BN entry projection (h path)."""
    x = b.add("relu", x, name=f"{prefix}_relu")
    x = b.add(
        "conv", x, name=f"{prefix}_conv", features=filters, kernel_size=1,
        use_bias=False,
    )
    return b.add("batch_norm", x, name=f"{prefix}_bn", eps=1e-3)


def _normal_cell(
    b: GraphBuilder, ip: str, p: str, filters: int, *, name: str
) -> str:
    """5-branch normal cell; concat of [p, x1..x5] -> 6*filters ch."""
    h = _squeeze(b, ip, filters, prefix=f"{name}_h")
    x1a = _sep_conv_block(b, h, filters, 5, prefix=f"{name}_left1")
    x1b = _sep_conv_block(b, p, filters, 3, prefix=f"{name}_right1")
    x1 = b.add("add", x1a, x1b, name=f"{name}_add1")
    x2a = _sep_conv_block(b, p, filters, 5, prefix=f"{name}_left2")
    x2b = _sep_conv_block(b, p, filters, 3, prefix=f"{name}_right2")
    x2 = b.add("add", x2a, x2b, name=f"{name}_add2")
    x3 = b.add(
        "avg_pool", h, name=f"{name}_left3", window=3, strides=1,
        padding="SAME",
    )
    x3 = b.add("add", x3, p, name=f"{name}_add3")
    x4a = b.add(
        "avg_pool", p, name=f"{name}_left4", window=3, strides=1,
        padding="SAME",
    )
    x4b = b.add(
        "avg_pool", p, name=f"{name}_right4", window=3, strides=1,
        padding="SAME",
    )
    x4 = b.add("add", x4a, x4b, name=f"{name}_add4")
    x5 = _sep_conv_block(b, h, filters, 3, prefix=f"{name}_left5")
    x5 = b.add("add", x5, h, name=f"{name}_add5")
    return b.add("concat", p, x1, x2, x3, x4, x5, name=name)


def _reduction_cell(
    b: GraphBuilder, ip: str, p: str, filters: int, *, name: str
) -> str:
    """Stride-2 cell; concat of [x2, x3, x4, x5] -> 4*filters ch."""
    h = _squeeze(b, ip, filters, prefix=f"{name}_h")
    x1a = _sep_conv_block(b, h, filters, 5, strides=2, prefix=f"{name}_left1")
    x1b = _sep_conv_block(b, p, filters, 7, strides=2, prefix=f"{name}_right1")
    x1 = b.add("add", x1a, x1b, name=f"{name}_add1")
    x2a = b.add(
        "max_pool", h, name=f"{name}_left2", window=3, strides=2,
        padding="SAME",
    )
    x2b = _sep_conv_block(b, p, filters, 7, strides=2, prefix=f"{name}_right2")
    x2 = b.add("add", x2a, x2b, name=f"{name}_add2")
    x3a = b.add(
        "avg_pool", h, name=f"{name}_left3", window=3, strides=2,
        padding="SAME",
    )
    x3b = _sep_conv_block(b, p, filters, 5, strides=2, prefix=f"{name}_right3")
    x3 = b.add("add", x3a, x3b, name=f"{name}_add3")
    x4 = b.add(
        "avg_pool", x1, name=f"{name}_left4", window=3, strides=1,
        padding="SAME",
    )
    x4 = b.add("add", x2, x4, name=f"{name}_add4")
    x5a = _sep_conv_block(b, x1, filters, 3, prefix=f"{name}_left5")
    x5b = b.add(
        "max_pool", h, name=f"{name}_right5", window=3, strides=2,
        padding="SAME",
    )
    x5 = b.add("add", x5a, x5b, name=f"{name}_add5")
    return b.add("concat", x2, x3, x4, x5, name=name)


def _build_nasnet(
    name: str,
    penultimate_filters: int,
    num_blocks: int,
    stem_filters: int,
    resolution: int,
    num_classes: int,
) -> Model:
    filters = penultimate_filters // 24
    b = GraphBuilder(name)
    x = b.input("input")
    x = b.add(
        "conv", x, name="stem_conv1", features=stem_filters, kernel_size=3,
        strides=2, padding="VALID", use_bias=False,
    )
    x = b.add("batch_norm", x, name="stem_bn1", eps=1e-3)
    cuts: list[str] = [x]

    # Track (node, channels, spatial-halvings) so _adjust knows whether p
    # needs the factorized reduction or just a channel projection. Each
    # inter-cell boundary carries the (cur, p) pair — collected as
    # multi-tensor cut bundles.
    pair_cuts: list[tuple[str, str]] = []

    def cell_chain():
        nonlocal x
        p, p_ch, p_lvl = None, stem_filters, 0
        cur, cur_ch, cur_lvl = x, stem_filters, 0

        def run(kind, f, cname):
            nonlocal p, p_ch, p_lvl, cur, cur_ch, cur_lvl
            adj = _adjust(
                b, p, cur, f,
                p_stride_mismatch=(p is not None and p_lvl < cur_lvl),
                p_channels=p_ch,
                prefix=cname,
            )
            prev, prev_ch, prev_lvl = cur, cur_ch, cur_lvl
            if kind == "normal":
                cur = _normal_cell(b, cur, adj, f, name=cname)
                cur_ch = 6 * f
            else:
                cur = _reduction_cell(b, cur, adj, f, name=cname)
                cur_ch, cur_lvl = 4 * f, cur_lvl + 1
            # p for the next cell is this cell's *input*; after _adjust,
            # its channel count is f (or unchanged when p was None).
            p, p_ch, p_lvl = prev, prev_ch, prev_lvl
            pair_cuts.append((cur, p))

        run("reduction", filters // 4, "stem_1")
        run("reduction", filters // 2, "stem_2")
        for i in range(num_blocks):
            run("normal", filters, f"cell_{i}")
        run("reduction", filters * 2, f"reduce_{num_blocks}")
        for i in range(num_blocks):
            run("normal", filters * 2, f"cell_{num_blocks + i + 1}")
        run("reduction", filters * 4, f"reduce_{2 * num_blocks}")
        for i in range(num_blocks):
            run("normal", filters * 4, f"cell_{2 * num_blocks + i + 1}")
        return cur

    x = cell_chain()
    # Every inter-cell boundary is a valid (cur, p) bundle; after the
    # final cell p is dropped, so that boundary is single-tensor.
    cuts.extend(pair_cuts[:-1])
    cuts.append(x)  # final cell's concat: its p companion is dropped here
    x = b.add("relu", x, name="final_relu")
    x = b.add("global_avg_pool", x, name="global_average_pooling2d")
    x = b.add("dense", x, name="predictions_dense", features=num_classes)
    x = b.add("softmax", x, name="predictions")
    return Model(
        name=name,
        graph=b.build(x),
        input_shape=(resolution, resolution, 3),
        cut_candidates=tuple(cuts),
    )


@register_model("nasnet_mobile")
def nasnet_mobile(num_classes: int = 1000) -> Model:
    return _build_nasnet("nasnet_mobile", 1056, 4, 32, 224, num_classes)


@register_model("nasnet_large")
def nasnet_large(num_classes: int = 1000) -> Model:
    return _build_nasnet("nasnet_large", 4032, 6, 96, 331, num_classes)
