"""Speculative decoding: draft-model proposal + target verification.

Decode is HBM-bound (one full weight read per token, models/gpt.py);
speculative decoding amortizes that read: a small DRAFT model proposes
k tokens autoregressively, then the TARGET verifies all k in ONE
forward (k positions through one weight read). Greedy acceptance keeps
the output EXACTLY the target's greedy decode — the correctness
contract the tests pin — while the target takes ~(accepted+1) tokens
per weight read instead of 1.

TPU-shaped mechanics on the existing KV-cache decoder:
  * verification reuses the decoder's prefill path (a T<=k+1 step is
    one compiled program, MXU-batched over positions);
  * REJECTION IS A POSITION REWIND: the cache masks attention by
    absolute position (gpt.py _block), so stale K/V rows beyond `pos`
    are never attended and the next write overwrites them — rollback
    costs a scalar update, no buffer copies;
  * the compiled step set is small and reused: T=1 (draft), T=k /
    T=k+1 (verify with/without a pending token), T=prompt (prefill).

The reference has no serving stack at all (it streams CNN frames,
reference src/test.py:30-41); this joins the beyond-reference serving
surface alongside dynamic batching and int8 weights.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def speculative_generate(
    target: Any,
    target_params: dict,
    draft: Any,
    draft_params: dict,
    prompt_ids: jax.Array,
    num_steps: int,
    *,
    k: int = 4,
) -> tuple[jax.Array, dict]:
    """Greedy speculative continuation of `prompt_ids` [1, T0].

    Returns (ids [1, T0 + num_steps], stats): ids are bit-identical to
    `target.generate(target_params, prompt_ids, num_steps)` at
    temperature 0, and stats carries the speedup evidence —
    `target_steps` (target weight reads taken, incl. prefill) vs
    `plain_steps`, and `acceptance` (the FRACTION of proposed tokens
    accepted, in [0, 1]; expected tokens per verify forward is
    acceptance*k + 1). Batch 1 only: acceptance length varies per
    element while the cache write head is one scalar.

    Invariant kept across rounds: the target cache covers `ids` except
    at most one trailing token; the draft cache covers `ids` except
    EXACTLY one trailing token (so each proposal round starts by
    feeding that token and reading the draft's next-token logits).
    """
    if prompt_ids.shape[0] != 1:
        raise ValueError("speculative decoding is batch-1 (scalar rewind)")
    for dec, name in ((target, "target"), (draft, "draft")):
        if getattr(dec, "rolling_cache", False):
            raise ValueError(
                f"{name} uses a rolling cache: rejected tokens have "
                "already overwritten live window slots, so a position "
                "rewind cannot undo them — use flat windowed caches "
                "for speculative decoding"
            )
    if prompt_ids.shape[1] < 1:
        raise ValueError("prompt must have at least one token")
    if k < 1:
        raise ValueError(f"k={k}: need at least one proposed token")
    t0 = prompt_ids.shape[1]
    for dec, name in ((target, "target"), (draft, "draft")):
        # +k: a verify round may overshoot num_steps before trimming.
        if t0 + num_steps + k > dec.cfg.max_len:
            raise ValueError(
                f"prompt {t0} + steps {num_steps} + k {k} exceeds the "
                f"{name} max_len {dec.cfg.max_len}"
            )

    tstep = target.make_step()
    dstep = draft.make_step()
    tcache = target.init_cache(1)
    dcache = draft.init_cache(1)

    # Prefill: target on the full prompt (its last logits are
    # P(next | prompt)); draft on all but the last token, establishing
    # the one-token-behind invariant.
    tlogits, tcache = tstep(target_params, tcache, prompt_ids)
    last_logits = tlogits[:, -1, :]
    if t0 > 1:
        _, dcache = dstep(draft_params, dcache, prompt_ids[:, :-1])

    ids = prompt_ids
    target_steps = 1
    rounds = 0
    accepted_total = 0

    while ids.shape[1] - t0 < num_steps:
        n0 = ids.shape[1]
        # 1. Draft proposes k tokens, starting from its missing last
        #    accepted token (greedy draft).
        feed = ids[:, -1:]
        proposals = []
        for _ in range(k):
            dlg, dcache = dstep(draft_params, dcache, feed)
            feed = jnp.argmax(dlg[:, -1, :], axis=-1)[:, None].astype(
                ids.dtype
            )
            proposals.append(feed)
        prop = jnp.concatenate(proposals, axis=1)  # [1, k]
        # Draft cache now covers ids + p1..p_{k-1} (p_k never fed).

        # 2. Target verifies in one forward: any not-yet-fed accepted
        #    token (0 or 1 of them) + the k proposals.
        t_missing = n0 - int(jax.device_get(tcache["pos"]))
        assert t_missing in (0, 1), t_missing
        verify_in = (
            jnp.concatenate([ids[:, n0 - t_missing :], prop], axis=1)
            if t_missing
            else prop
        )
        vlogits, tcache = tstep(target_params, tcache, verify_in)
        target_steps += 1
        # Prediction for proposal j comes from the logits of the
        # token before it: last_logits for p1 when nothing pended,
        # else in-round logits.
        base = last_logits if t_missing == 0 else vlogits[:, 0, :]
        preds = jnp.concatenate(
            [
                jnp.argmax(base, axis=-1)[:, None],
                jnp.argmax(
                    vlogits[:, t_missing : t_missing + k - 1, :], axis=-1
                ),
            ],
            axis=1,
        ).astype(ids.dtype)  # [1, k]

        matches = np.asarray(jax.device_get(preds[0] == prop[0]))
        a = k if matches.all() else int(matches.argmin())
        rounds += 1
        accepted_total += a

        if a == k:
            new = prop
            # Bonus: the verify forward already predicts the token
            # after p_k.
            last_logits = vlogits[:, t_missing + k - 1, :]
        else:
            # Target's own token replaces the first mismatch; it has
            # not been fed, so it becomes the target's pending token
            # (next round's base comes from in-round logits, so
            # last_logits is dead until the caches catch up).
            new = jnp.concatenate([prop[:, :a], preds[:, a : a + 1]], axis=1)
        ids = jnp.concatenate([ids, new], axis=1)
        n1 = ids.shape[1]

        # 3. Rewind write heads past rejected rows (position-masked,
        #    overwritten on the next write). Target covers n1 (full
        #    accept) or n0+a (its pending corrected token is new[-1]);
        #    draft always ends exactly one token behind ids.
        if a < k:
            tcache = {
                **tcache,
                "pos": jnp.asarray(n0 + a, tcache["pos"].dtype),
            }
        dcache = {
            **dcache,
            "pos": jnp.minimum(
                dcache["pos"], jnp.asarray(n1 - 1, dcache["pos"].dtype)
            ),
        }

    ids = ids[:, : t0 + num_steps]
    stats = {
        "target_steps": target_steps,
        "plain_steps": num_steps,
        "rounds": rounds,
        "acceptance": accepted_total / max(1, rounds * k),
    }
    return ids, stats
