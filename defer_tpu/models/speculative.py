"""Speculative decoding: draft-model proposal + target verification.

Decode is HBM-bound (one full weight read per token, models/gpt.py);
speculative decoding amortizes that read: a small DRAFT model proposes
k tokens autoregressively, then the TARGET verifies all k in ONE
forward (k positions through one weight read). Greedy acceptance keeps
the output EXACTLY the target's greedy decode — the correctness
contract the tests pin — while the target takes ~(accepted+1) tokens
per weight read instead of 1.

TPU-shaped mechanics on the existing KV-cache decoder:
  * verification reuses the decoder's prefill path (a T<=k+1 step is
    one compiled program, MXU-batched over positions);
  * REJECTION IS A POSITION REWIND: the cache masks attention by
    absolute position (gpt.py _block), so stale K/V rows beyond `pos`
    are never attended and the next write overwrites them — rollback
    costs a scalar update, no buffer copies;
  * the compiled step set is small and reused: T=1 / T=2 (draft — two
    tokens pend after a full-accept round's bonus token), T=k / T=k+1
    (verify with/without a pending token), T=prompt (prefill).

The reference has no serving stack at all (it streams CNN frames,
reference src/test.py:30-41); this joins the beyond-reference serving
surface alongside dynamic batching and int8 weights.

Reproducibility note (sampled mode, temperature > 0): sampled
speculative output is NOT stream-identical to
`target.generate(..., rng=key)` with the same seed — the full-accept
bonus draw consumes an extra PRNG split per round, so the key stream
depends on the draft and k. ARCHITECTURE.md "Speculative serving" has
the full account. Greedy mode (temperature 0) consumes no keys and
stays bit-identical to the target's greedy decode.

This is the SOLO loop (one request, flat caches on both models). For
serving-scale speculation over many concurrent requests, use
`PagedDecodeServer(spec_k=...)` (runtime/paged.py) — it shares this
module's accept rule via `batching.accept_lengths` and reports
through the same `defer_spec_*` metrics.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def speculative_generate(
    target: Any,
    target_params: dict,
    draft: Any,
    draft_params: dict,
    prompt_ids: jax.Array,
    num_steps: int,
    *,
    k: int = 4,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    min_p: float = 0.0,
    rng: jax.Array | None = None,
) -> tuple[jax.Array, dict]:
    """Speculative continuation of `prompt_ids` [1, T0].

    temperature 0 (default): greedy acceptance — ids are bit-identical
    to `target.generate(target_params, prompt_ids, num_steps)`.

    temperature > 0: SPECULATIVE SAMPLING (Leviathan/Chen): the draft
    SAMPLES k tokens from its filtered distribution q, the target's
    one verify forward yields p at every position, token x_j is
    accepted with probability min(1, p_j(x_j)/q_j(x_j)), and the first
    rejection resamples from the normalized residual max(p_j - q_j, 0)
    — the output distribution is EXACTLY what sampling the target
    directly would produce (the distribution-preservation property the
    tests check empirically). top_k/top_p filter BOTH p and q the same
    way sample_token would.

    Returns (ids [1, T0 + num_steps], stats): stats carries the
    speedup evidence — `target_steps` (target weight reads taken,
    incl. prefill) vs `plain_steps`, and `acceptance` (the FRACTION of
    proposed tokens accepted, in [0, 1]; expected tokens per verify
    forward is acceptance*k + 1). Batch 1 only: acceptance length
    varies per element while the cache write head is one scalar.

    Invariant kept across rounds: the target cache covers `ids` except
    at most one trailing token; the draft cache covers `ids` except
    one trailing token — two right after a full-accept round, whose
    bonus token (sampled free from the verify forward's final logits)
    is never fed to either model in-round. Each proposal round starts
    by feeding the draft whatever it is missing.
    """
    if prompt_ids.shape[0] != 1:
        raise ValueError(
            "speculative_generate is batch-1 (scalar rewind); for "
            "batched speculative serving use "
            "PagedDecodeServer(spec_k=...) — runtime/paged.py"
        )
    for dec, name in ((target, "target"), (draft, "draft")):
        if getattr(dec, "rolling_cache", False):
            raise ValueError(
                f"{name} uses a rolling cache: rejected tokens have "
                "already overwritten live window slots, so a position "
                "rewind cannot undo them — use flat windowed caches "
                "for speculative decoding"
            )
    if prompt_ids.shape[1] < 1:
        raise ValueError("prompt must have at least one token")
    if k < 1:
        raise ValueError(f"k={k}: need at least one proposed token")
    t0 = prompt_ids.shape[1]
    for dec, name in ((target, "target"), (draft, "draft")):
        # +k: a verify round may overshoot num_steps before trimming.
        if t0 + num_steps + k > dec.cfg.max_len:
            raise ValueError(
                f"prompt {t0} + steps {num_steps} + k {k} exceeds the "
                f"{name} max_len {dec.cfg.max_len}"
            )

    sampled = temperature > 0
    if sampled and rng is None:
        rng = jax.random.key(0)

    from defer_tpu.models.gpt import truncate_logits
    from defer_tpu.obs.serving import ServingMetrics
    from defer_tpu.runtime.batching import accept_lengths

    # Shared defer_spec_* instruments (obs/serving.py), labelled by
    # driver — fleet dashboards read the solo loop and the paged
    # server's spec_k mode side by side.
    obs = ServingMetrics("speculative")

    def filt(raw_logits):
        """Raw model logits -> FILTERED logits (temperature +
        top-k/top-p/min-p masking to -inf-scale) — applied identically
        to target p and draft q, as sample_token would. Sampling draws
        categorical on these directly (masked tokens exactly
        unsampleable); softmax of them is the matching distribution."""
        return truncate_logits(
            raw_logits.astype(jnp.float32) / temperature,
            top_k=top_k,
            top_p=top_p,
            min_p=min_p,
        )

    tstep = target.make_step()
    dstep = draft.make_step()
    tcache = target.init_cache(1)
    dcache = draft.init_cache(1)

    # Prefill: target on the full prompt (its last logits are
    # P(next | prompt)); draft on all but the last token, establishing
    # the one-token-behind invariant.
    tlogits, tcache = tstep(target_params, tcache, prompt_ids)
    last_logits = tlogits[:, -1, :]
    if t0 > 1:
        _, dcache = dstep(draft_params, dcache, prompt_ids[:, :-1])

    ids = prompt_ids
    target_steps = 1
    rounds = 0
    accepted_total = 0

    while ids.shape[1] - t0 < num_steps:
        n0 = ids.shape[1]
        # 1. Draft proposes k tokens, starting from the tokens it has
        #    not yet seen — one normally, two after a full-accept round
        #    (the bonus token was never fed). Greedy argmax, or samples
        #    from q with the per-position distributions kept for the
        #    accept test.
        # analysis: ignore[host-sync-in-hot-loop] one scalar sync per
        # speculative round to align the draft feed window
        d_pos = int(jax.device_get(dcache["pos"]))
        assert n0 - d_pos in (1, 2), (n0, d_pos)
        feed = ids[:, d_pos:]
        proposals = []
        q_dists = []
        for _ in range(k):
            dlg, dcache = dstep(draft_params, dcache, feed)
            if sampled:
                qlog = filt(dlg[:, -1, :])
                rng, sub = jax.random.split(rng)
                # Categorical on the masked logits directly — filtered
                # tokens are exactly unsampleable (same form as
                # sample_token).
                feed = jax.random.categorical(sub, qlog, axis=-1)[
                    :, None
                ].astype(ids.dtype)
                q_dists.append(jax.nn.softmax(qlog, axis=-1))
            else:
                feed = jnp.argmax(dlg[:, -1, :], axis=-1)[
                    :, None
                ].astype(ids.dtype)
            proposals.append(feed)
        prop = jnp.concatenate(proposals, axis=1)  # [1, k]
        # Draft cache now covers ids + p1..p_{k-1} (p_k never fed).

        # 2. Target verifies in one forward: any not-yet-fed accepted
        #    token (0 or 1 of them) + the k proposals.
        # analysis: ignore[host-sync-in-hot-loop] one scalar sync per
        # round to size the target verify window
        t_missing = n0 - int(jax.device_get(tcache["pos"]))
        assert t_missing in (0, 1), t_missing
        verify_in = (
            jnp.concatenate([ids[:, n0 - t_missing :], prop], axis=1)
            if t_missing
            else prop
        )
        vlogits, tcache = tstep(target_params, tcache, verify_in)
        target_steps += 1
        # Prediction for proposal j comes from the logits of the
        # token before it: last_logits for p1 when nothing pended,
        # else in-round logits.
        base = last_logits if t_missing == 0 else vlogits[:, 0, :]

        def p_raw(j):
            """Target logits predicting proposal j (0-indexed)."""
            return base if j == 0 else vlogits[:, t_missing + j - 1, :]

        if sampled:
            # Accept/reject per position: keep x_j with prob
            # min(1, p(x_j)/q(x_j)); first rejection resamples from
            # the normalized residual max(p - q, 0). Exactly the
            # target's sampling distribution, proven in the tests.
            # ONE batched device->host transfer carries everything the
            # host loop needs (the codebase keeps per-scalar syncs out
            # of decode loops — see EOS_POLL_EVERY).
            p_all = jax.nn.softmax(
                filt(jnp.concatenate([p_raw(j) for j in range(k)])),
                axis=-1,
            )  # [k, V] — one batched filter, not k row dispatches
            q_all = jnp.concatenate(q_dists, axis=0)  # [k, V]
            rng, sub_u, sub_r = jax.random.split(rng, 3)
            u_vec = jax.random.uniform(sub_u, (k,))
            sel = jnp.arange(k)
            # analysis: ignore[host-sync-in-hot-loop] the accept test
            # runs on host by design: ONE batched transfer of (u, p, q)
            # per verify round, not one per proposal
            host = jax.device_get(
                (u_vec, p_all[sel, prop[0]], q_all[sel, prop[0]])
            )
            # analysis: ignore[host-sync-in-hot-loop] views of the
            # already-fetched host tuple above — no device traffic
            u_h, p_h, q_h = (np.asarray(t) for t in host)
            a = k
            replacement = None
            for j in range(k):
                # analysis: ignore[host-sync-in-hot-loop] p_h/q_h are
                # host numpy arrays (fetched in the batch above)
                if u_h[j] < min(1.0, float(p_h[j]) / max(float(q_h[j]), 1e-38)):
                    continue
                a = j
                residual = jnp.maximum(p_all[j] - q_all[j], 0.0)
                total = residual.sum()
                # p == q exactly at this position would make the
                # residual empty, but then the accept ratio is 1 and
                # rejection is unreachable; guard anyway.
                src = jnp.where(total > 0, residual / total, p_all[j])
                replacement = jax.random.categorical(
                    jax.random.fold_in(sub_r, j),
                    jnp.log(jnp.maximum(src, 1e-38)),
                )[None, None].astype(ids.dtype)
                break
        else:
            preds = jnp.concatenate(
                [
                    jnp.argmax(base, axis=-1)[:, None],
                    jnp.argmax(
                        vlogits[:, t_missing : t_missing + k - 1, :],
                        axis=-1,
                    ),
                ],
                axis=1,
            ).astype(ids.dtype)  # [1, k]
            # analysis: ignore[host-sync-in-hot-loop] greedy accept
            # path: one batched transfer of (props, preds) per verify
            # round, into the accept rule the paged spec_k path shares
            props_h, preds_h = jax.device_get((prop, preds))
            a = int(accept_lengths(props_h, preds_h)[0])
            replacement = None if a == k else preds[:, a : a + 1]
        rounds += 1
        accepted_total += a
        obs.spec_rounds.inc()
        obs.spec_proposed.inc(k)
        if a:
            obs.spec_accepted.inc(a)
        # Per-round accepted-length observation (defer_spec_acceptance
        # is a histogram; its mean is acceptance * k).
        obs.spec_acceptance.observe(a)

        if a == k:
            # Bonus token (Leviathan/Chen): the verify forward's final
            # logits already predict the token after p_k — emitting it
            # is free, making every verify forward worth a+1 tokens on
            # full-accept rounds too. It has not been fed to either
            # model, so the target pends it (t_missing=1 next round)
            # and the draft starts two behind.
            fin = vlogits[:, t_missing + k - 1, :]
            if sampled:
                rng, sub_b = jax.random.split(rng)
                bonus = jax.random.categorical(
                    sub_b, filt(fin), axis=-1
                )[:, None].astype(ids.dtype)
            else:
                bonus = jnp.argmax(fin, axis=-1)[:, None].astype(
                    ids.dtype
                )
            new = jnp.concatenate([prop, bonus], axis=1)
        else:
            # The corrected token (target argmax in greedy mode, the
            # residual sample otherwise) replaces the first rejection;
            # it has not been fed, so it becomes the target's pending
            # token (next round's base comes from in-round logits, so
            # last_logits is dead until the caches catch up).
            new = jnp.concatenate([prop[:, :a], replacement], axis=1)
        ids = jnp.concatenate([ids, new], axis=1)
        n1 = ids.shape[1]

        # 3. Rewind write heads past rejected rows (position-masked,
        #    overwritten on the next write). Target covers n1 (full
        #    accept) or n0+a (its pending corrected token is new[-1]);
        #    draft always ends exactly one token behind ids.
        if a < k:
            tcache = {
                **tcache,
                "pos": jnp.asarray(n0 + a, tcache["pos"].dtype),
            }
        dcache = {
            **dcache,
            "pos": jnp.minimum(
                dcache["pos"], jnp.asarray(n1 - 1, dcache["pos"].dtype)
            ),
        }

    ids = ids[:, : t0 + num_steps]
    acceptance = accepted_total / max(1, rounds * k)
    stats = {
        "target_steps": target_steps,
        "plain_steps": num_steps,
        "rounds": rounds,
        "acceptance": acceptance,
    }
    return ids, stats
