"""Xception — depthwise-separable Inception successor, completing the
Keras-applications zoo the reference's partitioner targets (reference
src/dag_util.py:29-33 is model-generic over any single-in/single-out
Keras DAG; SURVEY.md §2 "Model zoo").

Entry flow (2 plain convs + 3 downsampling sepconv blocks with strided
1x1 residuals), middle flow (8 identical 728-channel residual blocks),
exit flow (one last downsampling block + 1536/2048 sepconvs). Every
block's add/pool output is a single-tensor articulation point, so all
12 block outputs are valid reference-style cuts.

Keras layer names match `keras.applications.Xception` (block names are
explicit in Keras; only the four residual-shortcut conv/BN pairs are
auto-named there — `conv2d`, `conv2d_1`, ... in build order — which
`_keras_name` reproduces for a freshly-built model)."""

from __future__ import annotations

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.models import Model, register_model

# Residual-shortcut pairs in Keras build order: our node prefix ->
# index of the auto-named Conv2D/BatchNormalization instance.
_RES_ORDER = ("block2", "block3", "block4", "block13")


def _keras_name(node: str) -> str:
    for i, blk in enumerate(_RES_ORDER):
        suffix = f"_{i}" if i else ""
        if node == f"{blk}_res_conv":
            return f"conv2d{suffix}"
        if node == f"{blk}_res_bn":
            return f"batch_normalization{suffix}"
    if node == "predictions_dense":
        return "predictions"
    return node


def _sepconv_bn(
    b: GraphBuilder,
    x: str,
    features: int,
    name: str,
    *,
    act_before: bool = True,
) -> str:
    """relu -> SeparableConv2D -> BN, Keras's pre-activation ordering
    (the activation is named for the conv it precedes)."""
    if act_before:
        x = b.add("relu", x, name=f"{name}_act")
    x = b.add(
        "separable_conv",
        x,
        name=name,
        features=features,
        kernel_size=3,
        padding="SAME",
        use_bias=False,
    )
    return b.add("batch_norm", x, name=f"{name}_bn", eps=1e-3)


def _down_block(
    b: GraphBuilder,
    x: str,
    features: int,
    blk: str,
    *,
    first_act: bool,
    last_features: int | None = None,
) -> str:
    """Two sepconvs + strided pool, added to a strided 1x1 shortcut."""
    res = b.add(
        "conv",
        x,
        name=f"{blk}_res_conv",
        features=last_features or features,
        kernel_size=1,
        strides=2,
        padding="SAME",
        use_bias=False,
    )
    res = b.add("batch_norm", res, name=f"{blk}_res_bn", eps=1e-3)
    x = _sepconv_bn(b, x, features, f"{blk}_sepconv1", act_before=first_act)
    # Keras names this activation for the conv it feeds (sepconv2).
    x = b.add("relu", x, name=f"{blk}_sepconv2_act")
    x = _sepconv_bn(
        b, x, last_features or features, f"{blk}_sepconv2", act_before=False
    )
    x = b.add(
        "max_pool",
        x,
        name=f"{blk}_pool",
        pool_size=3,
        strides=2,
        padding="SAME",
    )
    return b.add("add", x, res, name=f"{blk}_add")


@register_model("xception")
def xception(num_classes: int = 1000) -> Model:
    b = GraphBuilder("xception")
    x = b.input("input")

    # Entry flow: two VALID-padded stem convs...
    for i, (feat, stride) in enumerate(((32, 2), (64, 1)), start=1):
        x = b.add(
            "conv",
            x,
            name=f"block1_conv{i}",
            features=feat,
            kernel_size=3,
            strides=stride,
            padding="VALID",
            use_bias=False,
        )
        x = b.add("batch_norm", x, name=f"block1_conv{i}_bn", eps=1e-3)
        x = b.add("relu", x, name=f"block1_conv{i}_act")

    cuts: list[str] = []
    # ...then three downsampling sepconv blocks. block2's first sepconv
    # follows a ReLU already applied above, so it has no pre-act.
    x = _down_block(b, x, 128, "block2", first_act=False)
    cuts.append(x)
    x = _down_block(b, x, 256, "block3", first_act=True)
    cuts.append(x)
    x = _down_block(b, x, 728, "block4", first_act=True)
    cuts.append(x)

    # Middle flow: 8 identity-residual blocks of three 728 sepconvs.
    for bi in range(5, 13):
        res = x
        for si in range(1, 4):
            x = _sepconv_bn(b, x, 728, f"block{bi}_sepconv{si}")
        x = b.add("add", x, res, name=f"block{bi}_add")
        cuts.append(x)

    # Exit flow.
    x = _down_block(
        b, x, 728, "block13", first_act=True, last_features=1024
    )
    cuts.append(x)
    x = _sepconv_bn(b, x, 1536, "block14_sepconv1", act_before=False)
    x = b.add("relu", x, name="block14_sepconv1_act")
    x = _sepconv_bn(b, x, 2048, "block14_sepconv2", act_before=False)
    x = b.add("relu", x, name="block14_sepconv2_act")
    cuts.append(x)

    x = b.add("global_avg_pool", x, name="avg_pool")
    x = b.add("dense", x, name="predictions_dense", features=num_classes)
    x = b.add("softmax", x, name="predictions")
    return Model(
        name="xception",
        graph=b.build(x),
        input_shape=(299, 299, 3),
        cut_candidates=tuple(cuts),
        keras_name_map=_keras_name,
    )
