"""The DEFER facade — the reference's user-facing API, TPU-native.

Reference usage (src/test.py:20-21,44-50):

    defer = DEFER(['192.168.31.225', '192.168.31.215'])
    defer.run_defer(model, ["add_8"], input_q, output_q)   # in a thread

Here:

    defer = DEFER()                          # TPU mesh auto-discovered
    defer.run_defer(model, ["add_8"], input_q, output_q)

`run_defer` keeps the reference's blocking, queue-driven contract
(reference src/dispatcher.py:120-129) so driver scripts port unchanged,
but "dispatch" is partition + per-core jit compile + parameter placement
instead of sockets, and the stream loop is the async pipeline.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from defer_tpu.config import DeferConfig, normalize_cuts
from defer_tpu.graph.ir import Graph, GraphParams
from defer_tpu.graph.partition import partition
from defer_tpu.models import Model
from defer_tpu.obs.metrics import get_registry
from defer_tpu.parallel.mesh import pipeline_devices
from defer_tpu.parallel.pipeline import Pipeline
from defer_tpu.runtime.batching import split_output
from defer_tpu.runtime.host_io import STOP, ProgressMonitor
from defer_tpu.utils import profiling
from defer_tpu.utils.logging import get_logger
from defer_tpu.utils.memo import jit_cached
from defer_tpu.utils.sync import Retirer, hard_sync, hard_sync_timeout

log = get_logger(__name__)


class DEFER:
    """Pipeline-parallel inference orchestrator.

    Replaces the reference's dispatcher (reference src/dispatcher.py:22):
    instead of an IP list it takes an optional explicit device list
    (default: every device JAX can see — the TPU slice).
    """

    def __init__(
        self,
        devices: Sequence[jax.Device] | None = None,
        config: DeferConfig | None = None,
    ):
        self.devices = list(devices) if devices is not None else None
        self.config = config or DeferConfig()
        self._stop = threading.Event()
        self.last_pipeline: Pipeline | None = None
        # Filled by run_defer when config.probe_every > 0.
        self.last_stage_latencies: list[dict[str, float]] | None = None

    # -- construction ----------------------------------------------------

    def build_pipeline(
        self,
        model: Model | Graph | str,
        partition_layers: Sequence[str | Sequence[str]] | str | None,
        *,
        params: GraphParams | None = None,
        rng: jax.Array | None = None,
        batch_size: int = 1,
        replicas: int = 1,
    ) -> tuple[Any, Any]:
        """Partition + compile; returns (pipeline, example_input).

        The analogue of `_partition` + `_dispatchModels` (reference
        src/dispatcher.py:30-73): cut points become stage graphs, weight
        shipping becomes `device_put` of each stage's param slice.

        partition_layers="auto" picks FLOPs-balanced boundaries from
        the discovered candidates, one stage per device — the cut list
        the reference makes the user find by hand (reference
        src/test.py:24-28).

        replicas > 1 composes data parallelism with the stage chain:
        the whole pipeline is replicated that many times (over
        replicas x stages devices) and the stream fans microbatches
        across replicas round-robin — the scaling axis the reference
        doesn't have (its only lever is a deeper chain).
        """
        auto = (
            isinstance(partition_layers, str) and partition_layers == "auto"
        )
        cuts = () if auto else normalize_cuts(partition_layers)
        if isinstance(model, str):
            # The reference's wire format: a Keras model.to_json()
            # string (reference src/dispatcher.py:52).
            from defer_tpu.graph.keras_import import model_from_keras

            model, _ = model_from_keras(model)
        if isinstance(model, Model):
            graph = model.graph
            example = model.example_input(batch_size)
        else:
            graph = model
            example = None
        if params is None:
            if not isinstance(model, Model):
                raise ValueError("params required when passing a raw Graph")
            params = model.init(
                rng if rng is not None else jax.random.key(0),
                batch_size=batch_size,
                # Init in fp32 (stable RNG/statistics); Pipeline casts
                # to the storage dtype at placement.
                param_dtype=jnp.float32,
            )
        if auto:
            from defer_tpu.graph.partition import chain_boundaries
            from defer_tpu.utils.flops import balanced_cuts

            n_dev = len(
                self.devices if self.devices is not None else jax.devices()
            )
            cands = (
                model.cut_candidates
                if isinstance(model, Model) and model.cut_candidates
                else chain_boundaries(graph)
            )
            # Each replica needs its own stage slots: claiming all
            # n_dev for one replica's stages would make _compile wrap
            # further replicas round-robin onto the SAME chips —
            # contention, not throughput.
            n_stages = min(max(1, n_dev // max(1, replicas)), len(cands) + 1)
            if example is None:
                raise ValueError(
                    'partition_layers="auto" needs a Model (a raw Graph '
                    "has no input shape to balance FLOPs against)"
                )
            ex_leaf = jax.tree_util.tree_leaves(example)[0]
            cuts = tuple(
                balanced_cuts(
                    graph,
                    params,
                    tuple(int(d) for d in ex_leaf.shape),
                    n_stages,
                    cands,
                    input_dtype=ex_leaf.dtype,
                )
            )
            log.info("auto cuts (%d stages): %s", n_stages, cuts)
        stages = partition(graph, cuts) if cuts else [graph]
        pipe = self._compile(stages, params, replicas, None)
        self.last_pipeline = pipe
        # Retained for elastic re-dispatch after a stage failure.
        self._build_state = (stages, params, replicas)
        return pipe, example

    def _compile(
        self,
        stages: Sequence[Any],
        params: GraphParams,
        replicas: int,
        device_pool: Sequence[jax.Device] | None,
    ) -> Pipeline:
        pool = device_pool if device_pool is not None else self.devices
        n_phys = len(pool if pool is not None else jax.devices())
        if len(stages) * replicas > n_phys:
            log.warning(
                "%d stages x %d replicas oversubscribes %d physical "
                "devices; replicas will share chips",
                len(stages),
                replicas,
                n_phys,
            )
        if replicas > 1:
            from defer_tpu.parallel.data_parallel import ReplicatedPipeline

            devices = pipeline_devices(len(stages) * replicas, pool)
            log.info(
                "built %d stages x %d replicas over devices %s",
                len(stages),
                replicas,
                devices,
            )
            return ReplicatedPipeline(
                stages, params, devices, self.config, num_replicas=replicas
            )
        devices = pipeline_devices(len(stages), pool)
        log.info("built %d stages over devices %s", len(stages), devices)
        return Pipeline(stages, params, devices, self.config)

    # -- elastic recovery -------------------------------------------------

    def _healthy_devices(self, timeout_s: float = 10.0) -> list[jax.Device]:
        """Probe every candidate device with a tiny computation; a
        device that errors or misses the deadline is excluded from
        re-dispatch. Probes run concurrently under ONE shared deadline
        (hard_sync_timeout fetches in helper threads and dedupes by
        array), so n hung devices cost max(timeout), not n*timeout."""
        devs = self.devices if self.devices is not None else jax.devices()
        probes: list[tuple[jax.Device, Any]] = []
        healthy: list[jax.Device] = []
        for d in devs:
            try:
                probes.append(
                    (d, jax.device_put(jnp.zeros((), jnp.float32), d) + 1.0)
                )
            except Exception as e:  # noqa: BLE001 — exclusion is the point
                log.warning("device %s failed the health probe: %s", d, e)
        for _, probe in probes:  # start every fetch thread
            try:
                hard_sync_timeout(probe, 0.0)
            except Exception:  # noqa: BLE001 — surfaced in the wait below
                pass
        deadline = time.monotonic() + timeout_s
        for d, probe in probes:
            try:
                if hard_sync_timeout(
                    probe, max(0.0, deadline - time.monotonic())
                ):
                    healthy.append(d)
                else:
                    log.warning("device %s missed the health deadline", d)
            except Exception as e:  # noqa: BLE001 — exclusion is the point
                log.warning("device %s failed the health probe: %s", d, e)
        return healthy

    def _redispatch(self, cause: BaseException) -> Pipeline:
        """Rebuild the pipeline on the devices that still pass a health
        probe — the recovery the reference lacks entirely (node death
        hangs it forever, reference src/node.py:102-103)."""
        get_registry().counter(
            "defer_redispatch_total",
            "Elastic-recovery pipeline rebuilds after a device failure",
        ).inc()
        healthy = self._healthy_devices()
        if not healthy:
            raise RuntimeError(
                "re-dispatch impossible: no device passed the health probe"
            ) from cause
        stages, params, replicas = self._build_state
        log.warning(
            "re-dispatching %d stages (x%d replicas) onto %d healthy "
            "device(s) after: %s",
            len(stages),
            replicas,
            len(healthy),
            cause,
        )
        pipe = self._compile(stages, params, replicas, healthy)
        self.last_pipeline = pipe
        return pipe

    # -- streaming (the reference's run_defer contract) ------------------

    def run_defer(
        self,
        model: Model | Graph | str,
        partition_layers: Sequence[str | Sequence[str]] | str | None,
        input_stream: "queue.Queue[Any]",
        output_stream: "queue.Queue[Any]",
        *,
        params: GraphParams | None = None,
        rng: jax.Array | None = None,
        replicas: int = 1,
    ) -> None:
        """Blocking stream loop: consume input_stream, produce
        output_stream. Ends on a None/STOP sentinel or `stop()`.

        Signature mirrors reference src/dispatcher.py:120; `replicas`
        adds the data-parallel axis (see build_pipeline).
        """
        self._stop.clear()
        pipe, _ = self.build_pipeline(
            model, partition_layers, params=params, rng=rng,
            replicas=replicas,
        )
        monitor = ProgressMonitor(self.config.collective_timeout_s)

        def watchdog_sync(arr: Any) -> None:
            # Fetch-based barrier with a deadline so a stuck stage trips
            # the watchdog instead of hanging forever (utils/sync.py).
            # A barrier may cover many microbatches; on timeout we only
            # raise if the completed prefix stopped growing — genuinely
            # zero progress, matching collective_timeout_s semantics for
            # slow-but-healthy pipelines.
            last_ready = -1
            while not hard_sync_timeout(
                arr, self.config.collective_timeout_s
            ):
                ready = retirer.ready_count()
                if ready <= last_ready:
                    raise TimeoutError(
                        f"pipeline made no progress for "
                        f"{self.config.collective_timeout_s:.0f}s — a stage "
                        "or transfer is stuck"
                    )
                last_ready = ready

        # Replicated runtimes supply their own retirer bank: the shared
        # windowed-barrier trick is only sound within one device program
        # (see ReplicaRetirer in parallel/data_parallel.py).
        make = getattr(pipe, "make_retirer", None)
        retirer = (
            make(self.config.max_inflight, watchdog_sync)
            if make is not None
            else Retirer(self.config.max_inflight, sync=watchdog_sync)
        )

        # Dynamic batching: coalesce queue items into device batches
        # (runtime/batching.py) and split outputs back per item.
        # `splits` mirrors the dispatch FIFO: one sizes-list per
        # submitted batch, popped as its output retires.
        gatherer = None
        splits: "collections.deque[list[int]]" = collections.deque()
        if self.config.dynamic_batch_size > 1:
            from defer_tpu.runtime.batching import BatchGatherer

            gatherer = BatchGatherer(
                self.config.dynamic_batch_size, self.config.batch_wait_s
            )

        obs_items = get_registry().counter(
            "defer_stream_items_total",
            "Results delivered to the output stream by run_defer",
        )

        def emit(items: Sequence[Any]) -> None:
            for out in items:
                monitor.completed()
                if gatherer is None:
                    output_stream.put(out)
                    obs_items.inc()
                else:
                    for part in split_output(out, splits.popleft()):
                        output_stream.put(part)
                        obs_items.inc()

        # Unlike Pipeline.stream (pull-based), this loop must keep
        # emitting results while the input queue idles — the reference's
        # feed and result paths are independent threads for the same
        # reason (src/dispatcher.py:93-118).
        # Trace only a bounded window of the (potentially unbounded)
        # serving loop — an open-ended trace grows without limit.
        tracer = profiling.WindowTrace()
        try:
            self._stream_loop(
                pipe, input_stream, emit, retirer, monitor, tracer,
                gatherer, splits,
            )
        finally:
            tracer.close()

    def _stream_loop(
        self, pipe, input_stream, emit, retirer, monitor, tracer,
        gatherer=None, splits=None,
    ):
        since_probe = 0
        retries_left = self.config.redispatch_attempts
        eos = False
        while not self._stop.is_set() and not eos:
            if gatherer is None:
                try:
                    item = input_stream.get(timeout=0.05)
                except queue.Empty:
                    emit(retirer.collect())
                    monitor.check()
                    continue
                if item is None or item is STOP:
                    break
                sizes = None
            else:
                item, sizes, eos = gatherer.gather(input_stream)
                if item is None:
                    if eos:
                        break
                    emit(retirer.collect())
                    monitor.check()
                    continue
            monitor.submitted()
            tracer.tick()
            while True:
                try:
                    if sizes is not None:
                        splits.append(sizes)
                    emit(retirer.add(pipe.submit(item)))
                    break
                except Exception as e:  # noqa: BLE001 — recovery below
                    if retries_left <= 0:
                        raise
                    retries_left -= 1
                    # Completed results (including the barrier-failure
                    # spill) are still valid — emit them before
                    # dropping what can no longer finish.
                    try:
                        emit(retirer.collect())
                    except Exception:  # noqa: BLE001 — dead buffers
                        pass
                    lost = retirer.discard()
                    if splits is not None:
                        # Everything un-emitted was just discarded; the
                        # retry below re-appends this batch's sizes.
                        splits.clear()
                    if lost:
                        log.warning(
                            "dropping %d in-flight results of the failed "
                            "pipeline",
                            lost,
                        )
                        monitor.dropped(lost)
                        get_registry().counter(
                            "defer_inflight_dropped_total",
                            "In-flight results lost to pipeline failures",
                        ).inc(lost)
                    pipe = self._redispatch(e)
            monitor.check()
            since_probe += 1
            if (
                self.config.probe_every
                and since_probe >= self.config.probe_every
            ):
                # Synchronous per-stage latency probe; drain first so it
                # doesn't interleave with (and distort) in-flight work.
                since_probe = 0
                emit(retirer.flush())
                self.last_stage_latencies = pipe.probe_stage_latencies(
                    item, iters=3
                )
        # (A carried mismatch item can never survive to the sentinel:
        # gather() prepends the carry before it can consume STOP, so a
        # pending carry here means stop() interrupted the stream — and
        # after an explicit stop we must not submit new device work.)
        emit(retirer.flush())

    def stop(self) -> None:
        self._stop.set()


def run_local_inference(
    model: Model,
    *,
    batch_size: int = 1,
    duration_s: float = 10.0,
    params: GraphParams | None = None,
    compute_dtype: Any = None,
    example: Any = None,
) -> dict[str, float]:
    """Single-device baseline: jit the whole model on one core and loop.

    The analogue of the reference's `local_infer.py` (reference
    src/local_infer.py:16-23: preprocess one real image, loop
    `model.predict` for 10 min, count results) — this defines the
    denominator of every speedup claim. `example` supplies the looped
    input (e.g. a preprocessed real image batch); default is a ones
    tensor of the model's input shape.
    """
    cfg = DeferConfig()
    if compute_dtype is not None:
        cfg = cfg.replace(compute_dtype=compute_dtype)
    if params is None:
        params = model.init(jax.random.key(0), batch_size=batch_size)
    # Commit the example to device once — a host numpy example would
    # otherwise re-transfer every iteration and skew the baseline.
    x = (
        jax.device_put(jnp.asarray(example))
        if example is not None
        else model.example_input(batch_size)
    )
    # Count what actually runs — a caller-supplied example's leading
    # dim is the real batch; trusting batch_size would silently scale
    # the baseline metric.
    batch_size = int(x.shape[0]) if getattr(x, "ndim", 0) > 0 else 1

    def apply(p, v):
        if jnp.issubdtype(v.dtype, jnp.floating):
            v = v.astype(cfg.compute_dtype)
        return model.graph.apply(p, v)

    # `apply` is a fresh closure per call: plain jax.jit here re-traced
    # the whole model every time a bench re-entered (the memo.py
    # hazard). Zoo models share the entry by name (same name -> same
    # graph structure); anonymous models key on identity, which is
    # safe because the cached closure keeps `model` alive, so its id
    # can never be recycled onto a different model.
    ident = getattr(model, "name", None) or id(model)
    fn = jit_cached(
        apply, ("run_local_inference", ident, str(cfg.compute_dtype))
    )
    hard_sync(fn(params, x))  # compile

    count = 0
    t0 = time.perf_counter()
    retirer = Retirer(depth=16)
    while time.perf_counter() - t0 < duration_s:
        retirer.add(fn(params, x))
        count += 1
    # True completion barrier; device program order covers the rest.
    retirer.flush()
    dt = time.perf_counter() - t0
    return {
        "count": count,
        "seconds": dt,
        "batches_per_sec": count / dt,
        "items_per_sec": count * batch_size / dt,
    }
