"""Grammar/JSON-schema constrained decoding (compiler + runtime).

Host-side compile: `compile_regex(pattern, vocab)` /
`compile_json_schema(schema, vocab)` lower a constraint spec into a
dense token-level `TokenDFA` (dfa.py has the pipeline; schema.py the
JSON-schema subset). Device-side serve: pass the compiled DFAs as
`constraints={name: dfa}` to `DecodeServer` / `PagedDecodeServer`
(or any serve_* front-end) and select per request with
`SamplingParams(constraint=name)` — runtime.py documents the
stacked-table mask fold the tick programs use.
"""

from defer_tpu.constrain.dfa import (
    ConstraintError,
    TokenDFA,
    compile_regex,
    prune_dead_states,
)
from defer_tpu.constrain.runtime import FREE_CID, stack_token_dfas
from defer_tpu.constrain.schema import compile_json_schema, schema_to_regex

__all__ = [
    "ConstraintError",
    "TokenDFA",
    "compile_regex",
    "compile_json_schema",
    "schema_to_regex",
    "prune_dead_states",
    "stack_token_dfas",
    "FREE_CID",
]
