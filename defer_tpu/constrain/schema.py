"""JSON-schema subset -> regex lowering (compact canonical JSON).

`schema_to_regex` emits a pattern in the dialect dfa.py compiles —
and, by construction, a strict subset of Python `re` syntax, so a
test can check both `re.fullmatch(schema_to_regex(s), out)` and
`json.loads(out)` against the source schema.

Supported subset (production JSON-mode requests, not full
draft-2020): scalar types (`string`, `integer`, `number`,
`boolean`, `null`), `enum` of scalars, `array` with `items` /
`minItems` / `maxItems`, and `object` with `properties` — emitted
in declaration order with EVERY declared property present (the
canonical-form restriction that keeps the DFA linear in the schema;
`required` may name any subset and is implied). Whitespace is never
emitted: constrained decoding targets the compact form.
"""

from __future__ import annotations

import json

from defer_tpu.constrain.dfa import ConstraintError, TokenDFA, compile_regex

_REGEX_SPECIAL = set("()[]{}|*+?.\\^$")

#: Compact-JSON string body: any char except quote/backslash, or a
#: backslash escape. Matches what json.dumps emits for sane text.
_STRING = r'"([^"\\]|\\.)*"'
_INTEGER = r"-?(0|[1-9][0-9]*)"
_NUMBER = _INTEGER + r"(\.[0-9]+)?([eE][+-]?[0-9]+)?"

#: Default bound for arrays with no maxItems: an unbounded array is
#: representable (the DFA loops), so None would be fine for the
#: compiler — but an explicit schema bound keeps generated outputs
#: finite under greedy decoding, so only `maxItems: null` opts out.
_UNBOUNDED = object()


def _literal(text: str) -> str:
    return "".join(
        "\\" + c if c in _REGEX_SPECIAL else c for c in text
    )


def _json_literal(value) -> str:
    return _literal(json.dumps(value, separators=(",", ":")))


def schema_to_regex(schema: dict) -> str:
    """Lower one schema node to a regex over its compact JSON form."""
    if not isinstance(schema, dict):
        raise ConstraintError(
            f"schema nodes must be dicts, got {type(schema).__name__}"
        )
    if "enum" in schema:
        opts = schema["enum"]
        if not opts:
            raise ConstraintError("enum must be non-empty")
        return "(" + "|".join(_json_literal(v) for v in opts) + ")"
    t = schema.get("type")
    if t == "string":
        return _STRING
    if t == "integer":
        return _INTEGER
    if t == "number":
        return _NUMBER
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = schema_to_regex(schema.get("items", {"type": "string"}))
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems", _UNBOUNDED)
        if lo < 0 or (
            hi not in (None, _UNBOUNDED) and int(hi) < lo
        ):
            raise ConstraintError(
                f"array bounds minItems={lo} maxItems={hi} invalid"
            )
        if hi is _UNBOUNDED or hi is None:
            tail = f"({item})(,({item}))*"
            if lo > 1:
                tail = f"({item})(,({item})){{{lo - 1},}}"
            body = tail if lo >= 1 else f"({tail})?"
        else:
            hi = int(hi)
            if hi == 0:
                return r"\[\]"
            tail = f"({item})(,({item})){{{max(lo - 1, 0)},{hi - 1}}}"
            body = tail if lo >= 1 else f"({tail})?"
        return r"\[" + body + r"\]"
    if t == "object":
        props = schema.get("properties", {})
        if not props:
            return r"\{\}"
        fields = ",".join(
            f'{_json_literal(k)}:({schema_to_regex(v)})'
            for k, v in props.items()
        )
        return r"\{" + fields + r"\}"
    raise ConstraintError(
        f"unsupported schema node {schema!r}: need enum or type in "
        "{string, integer, number, boolean, null, array, object}"
    )


def compile_json_schema(schema: dict, vocab: list[str]) -> TokenDFA:
    """schema -> regex -> TokenDFA against `vocab` (dfa.compile_regex
    semantics, including compile-time unsatisfiability errors)."""
    return compile_regex(schema_to_regex(schema), vocab)
