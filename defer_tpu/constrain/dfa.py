"""Host-side constraint compiler: regex -> token-level DFA.

The serving runtime wants ONE dense table per constraint —
`transitions: int32 [S, V]` (-1 = token inadmissible) plus
`accepting: bool [S]` — because that shape folds into the batched
logits path as a single gather + mask (`transitions[state] >= 0`)
with zero host involvement per tick. Everything string-shaped
happens here, offline, once per (pattern, vocab) pair:

    1. parse the regex dialect below into an AST;
    2. Thompson-construct an NFA over CHARACTER sets, with the
       concrete alphabet = every character that appears in any vocab
       token string (so `.` and negated classes are exact over what
       the model can actually emit);
    3. subset-construct the character DFA;
    4. lift to tokens: token t maps state s to the state reached by
       running t's characters from s, or -1 if any step dies;
    5. prune by TOKEN co-reachability: any transition into a state
       that cannot reach an accepting state via token transitions is
       cut to -1. After this pass a compiled DFA can NEVER dead-end
       at runtime — every admissible token keeps an accepting state
       reachable — and an unsatisfiable (pattern, vocab) pair fails
       here, at compile time, instead of wedging a decode slot.

The dialect is a strict subset of Python `re` syntax (literals,
`.`, `[...]`/`[^...]` classes with ranges, `|`, groups, `*` `+` `?`
`{m}` `{m,}` `{m,n}`, and `\\d \\D \\w \\W \\s \\S` plus escaped
punctuation), so a test can re-validate emitted strings with
`re.fullmatch(pattern, text)` directly.

EOS is deliberately NOT part of the table: the runtime admits the
server's `eos_id` exactly in accepting states (the mask overwrites
that one column), and empty-string vocab entries are always
inadmissible — emitting one would advance the decode position
without advancing the constraint.
"""

from __future__ import annotations

import dataclasses
import string

import numpy as np


class ConstraintError(ValueError):
    """Raised for unparseable patterns and for (pattern, vocab) pairs
    whose token DFA cannot reach an accepting state — the compile-time
    surfacing of what would otherwise be a runtime dead-end."""


# -- pattern AST -------------------------------------------------------

_DIGITS = frozenset(string.digits)
_WORD = frozenset(string.ascii_letters + string.digits + "_")
_SPACE = frozenset(" \t\n\r\f\v")
_SPECIAL = frozenset("()[]{}|*+?.\\")


@dataclasses.dataclass(frozen=True)
class _CharSet:
    """A character predicate deferred until the alphabet is known:
    `chars` minus nothing (negate=False) or alphabet minus `chars`
    (negate=True). `.` is alphabet minus newline, per `re` default."""

    chars: frozenset
    negate: bool = False

    def resolve(self, alphabet: frozenset) -> frozenset:
        if self.negate:
            return alphabet - self.chars
        return self.chars & alphabet


_ANY = _CharSet(frozenset("\n"), negate=True)


@dataclasses.dataclass(frozen=True)
class _Lit:
    cs: _CharSet


@dataclasses.dataclass(frozen=True)
class _Cat:
    parts: tuple


@dataclasses.dataclass(frozen=True)
class _Alt:
    parts: tuple


@dataclasses.dataclass(frozen=True)
class _Rep:
    node: object
    lo: int
    hi: int | None  # None = unbounded


class _Parser:
    """Recursive-descent parser for the dialect above."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def _err(self, msg: str) -> ConstraintError:
        return ConstraintError(
            f"bad pattern at index {self.i}: {msg} (in {self.p!r})"
        )

    def _peek(self) -> str | None:
        return self.p[self.i] if self.i < len(self.p) else None

    def _take(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise self._err(f"unexpected {self.p[self.i]!r}")
        return node

    def _alt(self):
        parts = [self._cat()]
        while self._peek() == "|":
            self._take()
            parts.append(self._cat())
        return parts[0] if len(parts) == 1 else _Alt(tuple(parts))

    def _cat(self):
        parts = []
        while self._peek() not in (None, "|", ")"):
            parts.append(self._repeat())
        if not parts:
            return _Cat(())  # empty branch matches ""
        return parts[0] if len(parts) == 1 else _Cat(tuple(parts))

    def _repeat(self):
        node = self._atom()
        while True:
            c = self._peek()
            if c == "*":
                self._take()
                node = _Rep(node, 0, None)
            elif c == "+":
                self._take()
                node = _Rep(node, 1, None)
            elif c == "?":
                self._take()
                node = _Rep(node, 0, 1)
            elif c == "{":
                node = _Rep(node, *self._braces())
            else:
                return node

    def _braces(self) -> tuple[int, int | None]:
        self._take()  # '{'
        lo = self._int("repeat lower bound")
        hi: int | None = lo
        if self._peek() == ",":
            self._take()
            hi = None if self._peek() == "}" else self._int(
                "repeat upper bound"
            )
        if self._peek() != "}":
            raise self._err("expected '}'")
        self._take()
        if hi is not None and hi < lo:
            raise self._err(f"repeat bounds {{{lo},{hi}}} inverted")
        return lo, hi

    def _int(self, what: str) -> int:
        digits = ""
        while self._peek() is not None and self._peek().isdigit():
            digits += self._take()
        if not digits:
            raise self._err(f"expected {what}")
        return int(digits)

    def _atom(self):
        c = self._peek()
        if c is None:
            raise self._err("pattern ended early")
        if c == "(":
            self._take()
            node = self._alt()
            if self._peek() != ")":
                raise self._err("unclosed group")
            self._take()
            return node
        if c == "[":
            return _Lit(self._char_class())
        if c == ".":
            self._take()
            return _Lit(_ANY)
        if c == "\\":
            return _Lit(self._escape())
        if c in _SPECIAL:
            raise self._err(f"unescaped {c!r}")
        self._take()
        return _Lit(_CharSet(frozenset(c)))

    def _escape(self) -> _CharSet:
        self._take()  # backslash
        if self._peek() is None:
            raise self._err("dangling backslash")
        e = self._take()
        table = {
            "d": _CharSet(_DIGITS),
            "D": _CharSet(_DIGITS, negate=True),
            "w": _CharSet(_WORD),
            "W": _CharSet(_WORD, negate=True),
            "s": _CharSet(_SPACE),
            "S": _CharSet(_SPACE, negate=True),
            "n": _CharSet(frozenset("\n")),
            "t": _CharSet(frozenset("\t")),
            "r": _CharSet(frozenset("\r")),
        }
        if e in table:
            return table[e]
        return _CharSet(frozenset(e))  # escaped punctuation/literal

    def _char_class(self) -> _CharSet:
        self._take()  # '['
        negate = self._peek() == "^"
        if negate:
            self._take()
        chars: set = set()
        negsets: list[_CharSet] = []
        if self._peek() == "]":  # leading ']' is a literal, as in re
            chars.add(self._take())
        while self._peek() not in (None, "]"):
            if self._peek() == "\\":
                cs = self._escape()
                if cs.negate:
                    negsets.append(cs)
                else:
                    chars |= cs.chars
                continue
            lo = self._take()
            if self._peek() == "-" and self.p[self.i + 1 : self.i + 2] not in (
                "", "]"
            ):
                self._take()
                hi = self._take()
                if ord(hi) < ord(lo):
                    raise self._err(f"range {lo}-{hi} inverted")
                chars |= {chr(o) for o in range(ord(lo), ord(hi) + 1)}
            else:
                chars.add(lo)
        if self._peek() != "]":
            raise self._err("unclosed character class")
        self._take()
        if negsets:
            # e.g. [\D...]: fold by De Morgan into one deferred set.
            if len(negsets) > 1 or chars or negate:
                raise self._err(
                    "negated escapes may not be combined inside a class"
                )
            return negsets[0]
        return _CharSet(frozenset(chars), negate=negate)


# -- NFA / DFA construction -------------------------------------------


class _NFA:
    """Thompson NFA: eps edges plus char-set edges, one accept."""

    def __init__(self):
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[frozenset, int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def build(self, node, alphabet: frozenset) -> tuple[int, int]:
        """Returns (start, accept) fragment for `node`."""
        if isinstance(node, _Lit):
            s, a = self.state(), self.state()
            self.edges[s].append((node.cs.resolve(alphabet), a))
            return s, a
        if isinstance(node, _Cat):
            s = a = self.state()
            for part in node.parts:
                ps, pa = self.build(part, alphabet)
                self.eps[a].append(ps)
                a = pa
            return s, a
        if isinstance(node, _Alt):
            s, a = self.state(), self.state()
            for part in node.parts:
                ps, pa = self.build(part, alphabet)
                self.eps[s].append(ps)
                self.eps[pa].append(a)
            return s, a
        if isinstance(node, _Rep):
            s = a = self.state()
            for _ in range(node.lo):
                ps, pa = self.build(node.node, alphabet)
                self.eps[a].append(ps)
                a = pa
            if node.hi is None:
                ps, pa = self.build(node.node, alphabet)
                self.eps[a].append(ps)
                self.eps[pa].append(ps)
                end = self.state()
                self.eps[a].append(end)
                self.eps[pa].append(end)
                return s, end
            for _ in range(node.hi - node.lo):
                ps, pa = self.build(node.node, alphabet)
                self.eps[a].append(ps)
                end = self.state()
                self.eps[a].append(end)
                self.eps[pa].append(end)
                a = end
            return s, a
        raise AssertionError(f"unknown node {node!r}")

    def closure(self, states: frozenset) -> frozenset:
        seen = set(states)
        stack = list(states)
        while stack:
            for t in self.eps[stack.pop()]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)


def _char_dfa(
    pattern: str, alphabet: frozenset
) -> tuple[dict[tuple[int, str], int], set[int], int]:
    """Subset construction: (transitions, accepting states, count)."""
    ast_root = _Parser(pattern).parse()
    nfa = _NFA()
    start, accept = nfa.build(ast_root, alphabet)
    d0 = nfa.closure(frozenset([start]))
    ids: dict[frozenset, int] = {d0: 0}
    order = [d0]
    trans: dict[tuple[int, str], int] = {}
    i = 0
    while i < len(order):
        cur = order[i]
        # Group the outgoing char sets once per state, then move per
        # char — alphabets are small (chars the vocab can emit).
        chars: set = set()
        for st in cur:
            for cs, _ in nfa.edges[st]:
                chars |= cs
        for c in sorted(chars):
            nxt = frozenset(
                t
                for st in cur
                for cs, t in nfa.edges[st]
                if c in cs
            )
            nxt = nfa.closure(nxt)
            if nxt not in ids:
                ids[nxt] = len(order)
                order.append(nxt)
            trans[(ids[cur], c)] = ids[nxt]
        i += 1
    accepting = {ids[s] for s in order if accept in s}
    return trans, accepting, len(order)


# -- the token-level artifact -----------------------------------------


@dataclasses.dataclass
class TokenDFA:
    """Dense token-level DFA over a fixed vocabulary.

    `transitions[s, t]` is the state after emitting token t from
    state s, or -1 when t is inadmissible there; `accepting[s]` marks
    states where the constraint is satisfied (the runtime admits eos
    exactly there). `start` is always a valid row index. `pattern`
    is carried for error messages and for tests to re-validate
    emitted strings against the source regex."""

    transitions: np.ndarray  # int32 [S, V]
    accepting: np.ndarray  # bool [S]
    start: int = 0
    pattern: str = ""

    def __post_init__(self):
        self.transitions = np.asarray(self.transitions, np.int32)
        self.accepting = np.asarray(self.accepting, bool)
        if self.transitions.ndim != 2:
            raise ConstraintError(
                f"transitions must be [S, V], got shape "
                f"{self.transitions.shape}"
            )
        if self.accepting.shape != (self.transitions.shape[0],):
            raise ConstraintError(
                f"accepting shape {self.accepting.shape} does not "
                f"match {self.transitions.shape[0]} states"
            )
        if not 0 <= self.start < self.transitions.shape[0]:
            raise ConstraintError(f"start state {self.start} out of range")

    @property
    def num_states(self) -> int:
        return int(self.transitions.shape[0])

    @property
    def vocab_size(self) -> int:
        return int(self.transitions.shape[1])

    def step(self, state: int, token: int) -> int:
        """Host-side single step (tests/validation): -1 = rejected."""
        return int(self.transitions[state, token])

    def admissible(self, state: int) -> np.ndarray:
        """Host-side mask row [V] (eos column NOT special-cased)."""
        return self.transitions[state] >= 0

    def walk(self, tokens) -> int:
        """Run a token sequence from start; returns the final state or
        -1 the moment any step is inadmissible."""
        s = self.start
        for t in tokens:
            s = int(self.transitions[s, int(t)])
            if s < 0:
                return -1
        return s


def prune_dead_states(
    transitions: np.ndarray, accepting: np.ndarray
) -> np.ndarray:
    """Cut every transition into a state that cannot reach an
    accepting state through token transitions (backward co-
    reachability fixpoint). Returns the pruned copy; the caller
    decides what a dead start state means."""
    trans = np.array(transitions, np.int32, copy=True)
    live = set(np.flatnonzero(accepting).tolist())
    changed = True
    while changed:
        changed = False
        for s in range(trans.shape[0]):
            if s in live:
                continue
            tgt = trans[s]
            if any(int(t) in live for t in tgt[tgt >= 0]):
                live.add(s)
                changed = True
    for s in range(trans.shape[0]):
        row = trans[s]
        bad = (row >= 0) & ~np.isin(row, list(live) or [-1])
        row[bad] = -1
    return trans


def compile_regex(pattern: str, vocab: list[str]) -> TokenDFA:
    """Lower `pattern` against a token-string vocabulary (index =
    token id) into a TokenDFA. Raises ConstraintError when the
    pattern cannot match any token sequence from this vocabulary —
    the unsatisfiable case a runtime must never be handed."""
    if not vocab:
        raise ConstraintError("empty vocabulary")
    alphabet = frozenset(c for tok in vocab for c in tok)
    ctrans, caccept, n_states = _char_dfa(pattern, alphabet)
    V = len(vocab)
    trans = np.full((n_states, V), -1, np.int32)
    for tid, tok in enumerate(vocab):
        if not tok:
            continue  # empty-string tokens never admissible
        for s in range(n_states):
            cur = s
            for c in tok:
                nxt = ctrans.get((cur, c))
                if nxt is None:
                    cur = -1
                    break
                cur = nxt
            trans[s, tid] = cur
    accepting = np.zeros((n_states,), bool)
    accepting[list(caccept)] = True
    trans = prune_dead_states(trans, accepting)
    if not accepting[0] and not (trans[0] >= 0).any():
        raise ConstraintError(
            f"pattern {pattern!r} is unsatisfiable with this "
            f"vocabulary: no token sequence can reach an accepting "
            "state"
        )
    return TokenDFA(trans, accepting, start=0, pattern=pattern)
