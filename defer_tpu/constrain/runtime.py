"""Device-side constraint runtime helpers shared by both servers.

A server constructed with `constraints={name: TokenDFA}` stacks every
DFA (plus one synthetic accept-everything DFA at index 0, the FREE
row) into two padded device tables:

    trans_all: int32 [C, S_max, V]   (-1 = inadmissible / padding)
    acc_all:   bool  [C, S_max]

Each slot then carries two int32 policy rows in SlotSampler —
`cid` (which constraint; 0 = free) and `cstate` (current DFA state)
— and the per-tick mask fold is one gather plus one where:

    row  = trans_all[cid, cstate]            # [B, V]
    mask = row >= 0; mask[:, eos] = acc      # eos iff accepting
    ll   = where(mask, ll, finfo.min)
    state' = max(row[nxt], 0)                # after sampling nxt

For a FREE row the synthetic DFA makes `mask` all-True, so the fold
is `where(True, ll, _)` — an exact bitwise no-op — which is what
lets a mixed batch share one constrained program without perturbing
its unconstrained rows. (Servers still trace the constrained
program only while a constrained row is actually live, dispatched
by a host flag like SlotSampler.row_sort, so `constraints=None`
serving never sees these ops at all.)

All helpers here are shape-polymorphic jnp code: they trace inside
the jitted window/spec programs and run eagerly on the K=1 tick.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from defer_tpu.constrain.dfa import ConstraintError, TokenDFA

#: cid value of an unconstrained slot (row 0 of the stacked tables).
FREE_CID = 0


def stack_token_dfas(
    constraints: dict[str, TokenDFA], vocab_size: int
) -> tuple[dict[str, int], jnp.ndarray, jnp.ndarray]:
    """Validate + stack named DFAs into the padded device tables.
    Returns (name -> cid, trans_all [C, S_max, V], acc_all [C, S_max]);
    cid 0 is the synthetic free row, names take 1..C-1 sorted."""
    if not constraints:
        raise ConstraintError("constraints= given but empty")
    for name, dfa in constraints.items():
        if not isinstance(dfa, TokenDFA):
            raise ConstraintError(
                f"constraint {name!r} is {type(dfa).__name__}, "
                "expected a constrain.TokenDFA"
            )
        if dfa.vocab_size != vocab_size:
            raise ConstraintError(
                f"constraint {name!r} compiled for vocab "
                f"{dfa.vocab_size}, model vocab is {vocab_size}"
            )
    names = sorted(constraints)
    s_max = max(
        [1] + [constraints[n].num_states for n in names]
    )
    C = len(names) + 1
    trans = np.full((C, s_max, vocab_size), -1, np.int32)
    acc = np.zeros((C, s_max), bool)
    # Free row: one state, every token loops, always accepting — the
    # exact-no-op mask for unconstrained slots in a constrained batch.
    trans[FREE_CID, 0, :] = 0
    acc[FREE_CID, 0] = True
    cids = {}
    for k, name in enumerate(names, start=1):
        dfa = constraints[name]
        trans[k, : dfa.num_states] = dfa.transitions
        acc[k, : dfa.num_states] = dfa.accepting
        cids[name] = k
    return cids, jnp.asarray(trans), jnp.asarray(acc)


def resolve_constraint(name, ctrans, cnames, cdfas) -> int:
    """Constraint name -> stacked-table cid, validating at submit
    time (unknown names and start-state dead ends must fail the
    caller, never wedge a slot). Shared by both servers'
    `_resolve_constraint`."""
    if name is None:
        return FREE_CID
    if ctrans is None:
        raise ValueError(
            f"sampling requests constraint {name!r} but the "
            "server was built without constraints="
        )
    cid = cnames.get(name)
    if cid is None:
        raise ValueError(
            f"unknown constraint {name!r}; registered: "
            f"{sorted(cnames)}"
        )
    dfa = cdfas[cid]
    if not dfa.accepting[dfa.start] and not (
        dfa.transitions[dfa.start] >= 0
    ).any():
        raise ValueError(
            f"constraint {name!r} admits no first token (dead "
            "start state — compile via constrain.compile_regex "
            "to get dead states pruned at build time)"
        )
    return cid


def constrain_rows(trans_all, acc_all, cid, cstate):
    """Per-slot transition row + accepting bit: ([B, V], [B])."""
    return trans_all[cid, cstate], acc_all[cid, cstate]


def constrain_mask(row, acc, eos_id: int):
    """Admissibility mask [B, V]: table says yes, except the eos
    column which is admitted exactly in accepting states."""
    mask = row >= 0
    return mask.at[:, eos_id].set(acc)


def fold_mask(ll, mask):
    """Mask-fold into the logits path; finfo.min (not -inf) so a
    sampled row's softmax stays NaN-free even near a dead end."""
    return jnp.where(mask, ll, jnp.finfo(ll.dtype).min)


def advance_state(row, cstate, nxt, advance):
    """Post-sample state update: rows with `advance` move to
    row[nxt] (clamped — the eos/forced column may be -1), others
    keep their state."""
    new = jnp.take_along_axis(row, nxt[:, None].astype(jnp.int32), 1)[
        :, 0
    ]
    return jnp.where(advance, jnp.maximum(new, 0), cstate)


def masked_frac(mask, active):
    """Fraction of the vocabulary the constraint masked off, per row
    (float32 [B]); inactive rows report 0."""
    frac = 1.0 - jnp.mean(mask, axis=-1, dtype=jnp.float32)
    return jnp.where(active, frac, 0.0)
