"""Per-instance memoization for jitted step builders.

jax.jit's compilation cache is keyed on the function OBJECT: a method
that returns `jax.jit(fresh_closure)` on every call re-traces and
re-compiles every shape each time. Every `make_step`-style builder in
the model layer routes through this one helper instead.
"""

from __future__ import annotations

from typing import Any, Callable


def cached_step(obj: Any, key: Any, build: Callable[[], Any]) -> Any:
    """Build-once per (instance, key); subsequent calls return the same
    callable so jit's cache keeps working."""
    cache = getattr(obj, "_step_cache", None)
    if cache is None:
        cache = obj._step_cache = {}
    if key not in cache:
        cache[key] = build()
    return cache[key]
