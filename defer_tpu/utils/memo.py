"""Per-instance memoization for jitted step builders.

jax.jit's compilation cache is keyed on the function OBJECT: a method
that returns `jax.jit(fresh_closure)` on every call re-traces and
re-compiles every shape each time. Every `make_step`-style builder in
the model layer routes through this one helper instead.
"""

from __future__ import annotations

from typing import Any, Callable

import jax


def cached_step(obj: Any, key: Any, build: Callable[[], Any]) -> Any:
    """Build-once per (instance, key); subsequent calls return the same
    callable so jit's cache keeps working."""
    cache = getattr(obj, "_step_cache", None)
    if cache is None:
        cache = obj._step_cache = {}
    if key not in cache:
        cache[key] = build()
    return cache[key]


_JIT_CACHE: dict[Any, Any] = {}


def jit_cached(
    fn: Callable[..., Any], static_key: Any, **jit_kwargs: Any
) -> Any:
    """Process-wide keyed jit cache for closures with no instance to
    hang a `_step_cache` on.

    `jax.jit(fresh_closure)` in a per-call function re-traces every
    call; this returns one jitted callable per (static_key, jit
    options) forever after. Contract: `static_key` must fully
    determine the closure's behavior — the FIRST closure built for a
    key wins, and later semantically-different closures under the same
    key would silently run the first one's trace. Key on everything
    the closure captures (model name, dtype, flags), exactly like
    static_argnums for captured state.

    Entries are never evicted (the cache holds whatever the closure
    captures alive), so keys must come from a bounded set — config
    values, not per-request data.
    """
    key = (static_key, tuple(sorted(jit_kwargs.items())))
    got = _JIT_CACHE.get(key)
    if got is None:
        got = _JIT_CACHE[key] = jax.jit(fn, **jit_kwargs)
    return got
