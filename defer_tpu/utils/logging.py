"""Structured logging.

The reference's only observability is `print("[DEBUG] ...")` scattered
through dispatcher and node (e.g. reference src/dispatcher.py:63,69,96,
src/node.py:29,32,41). Here: standard `logging` with one shared
formatter, quiet by default, DEBUG via DEFER_TPU_LOGLEVEL.
"""

from __future__ import annotations

import logging
import os

_CONFIGURED = False


def get_logger(name: str) -> logging.Logger:
    global _CONFIGURED
    if not _CONFIGURED:
        level = os.environ.get("DEFER_TPU_LOGLEVEL", "WARNING").upper()
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
        root = logging.getLogger("defer_tpu")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _CONFIGURED = True
    return logging.getLogger(name)
