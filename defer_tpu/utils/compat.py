"""Version shims for the narrow band of jax APIs whose home moved.

`shard_map` graduated from `jax.experimental.shard_map` to `jax.shard_map`
(and its `check_rep` knob was renamed `check_vma` along the way). The
serving stack runs on whichever jax the image bakes in, so every caller
goes through this one wrapper instead of guessing the import site.
"""

from __future__ import annotations

import functools
from typing import Any

import jax

if hasattr(jax, "shard_map"):
    _shard_map_impl = jax.shard_map
else:  # pragma: no cover - depends on the installed jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def shard_map(
    f: Any,
    mesh: Any,
    *,
    in_specs: Any,
    out_specs: Any,
    check_rep: bool = True,
) -> Any:
    """`jax.shard_map` with the replication-check kwarg normalized:
    pass `check_rep=` here regardless of what the installed jax calls
    it. Bodies that end in an explicit collective whose output
    replication the checker cannot infer (e.g. a tiled `all_gather` of
    vocab-sharded logits) pass check_rep=False; everything else keeps
    the checker on."""
    try:
        return _shard_map_impl(
            f, mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_rep,
        )
    except TypeError:
        return _shard_map_impl(
            f, mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_rep,
        )


@functools.lru_cache(maxsize=None)
def has_shard_map() -> bool:
    """True when some shard_map implementation is importable (always,
    on the jax versions this repo supports) — kept as a gate so callers
    can degrade to single-device serving instead of crashing if a
    stripped-down jax build drops the experimental module."""
    return _shard_map_impl is not None
