"""One place for the JAX platform-selection workaround.

The env default alone is not enough on hosts whose site customization
pre-imports jax and forces its platform via config.update, which
overrides the env-derived default — so we override back, before first
backend use. (Verified empirically: without this, JAX_PLATFORMS=cpu
runs still initialized the site platform.)
"""

from __future__ import annotations

import os


def honor_env_platform() -> None:
    """Apply $JAX_PLATFORMS to the live jax config if set. Call before
    first backend use in every entry point (bench, CLI, workers)."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)
