"""One place for the JAX platform-selection workaround.

The env default alone is not enough on hosts whose site customization
pre-imports jax and forces its platform via config.update, which
overrides the env-derived default — so we override back, before first
backend use. (Verified empirically: without this, JAX_PLATFORMS=cpu
runs still initialized the site platform.)
"""

from __future__ import annotations

import os


def honor_env_platform() -> None:
    """Apply $JAX_PLATFORMS to the live jax config if set. Call before
    first backend use in every entry point (bench, CLI, workers)."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)


class BackendInitHang(RuntimeError):
    """Backend init exceeded its deadline (wedged device transport) —
    distinct from an ERROR raised by init, which is retryable."""


def devices_with_deadline(timeout_s: float):
    """jax.devices() bounded by a deadline: a wedged TPU tunnel HANGS
    backend init rather than erroring, which would otherwise stall any
    entry point that touches the backend (bench headline, CLI info)
    forever. NOTE: on timeout the probe thread remains blocked inside
    xla_bridge holding its module lock — treat the process as unable
    to use that backend and exit/fallback, don't retry in-process."""
    import threading

    import jax

    result: dict = {}

    def probe() -> None:
        try:
            result["devs"] = jax.devices()
        except BaseException as e:  # noqa: BLE001 — relayed below
            result["err"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise BackendInitHang(
            f"backend init did not complete within {timeout_s:.0f}s "
            "(wedged device transport?)"
        )
    if "err" in result:
        raise result["err"]
    return result["devs"]
