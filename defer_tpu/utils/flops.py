"""Analytic FLOPs accounting + TPU peak-FLOPs table -> MFU.

The reference reports raw images/sec only (reference src/test.py:40-41);
absolute hardware efficiency is invisible. Here the benchmark derives
model FLOPs analytically from the IR (one node walk over inferred
shapes) and divides achieved FLOP/s by the chip's peak to report MFU —
the number that says how much of the TPU the pipeline actually uses.
"""

from __future__ import annotations

from typing import Any, Sequence

from defer_tpu.graph.ir import Graph, GraphParams

# Per-chip dense peak FLOP/s by `jax.Device.device_kind` substring,
# bf16 (the benchmark compute dtype). Public figures from Google's TPU
# system documentation.
_PEAK_BF16: tuple[tuple[str, float], ...] = (
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6 lite", 918e12),  # Trillium
    ("v6e", 918e12),
    ("v4 lite", 138e12),  # v4i
    ("v4", 275e12),
    ("v3", 123e12),  # per chip (2 cores)
    ("v2", 45e12),
)


def lookup_device_table(
    device_kind: str, table: tuple[tuple[str, float], ...]
) -> float | None:
    """First (substring, value) match for a device kind — the one
    lookup shared by the peak-FLOPs and peak-bandwidth tables (order
    matters: more specific keys like 'v4 lite' come before 'v4')."""
    kind = device_kind.lower()
    for key, val in table:
        if key in kind:
            return val
    return None


def peak_flops(device_kind: str) -> float | None:
    """Dense bf16 peak FLOP/s for a TPU device kind; None if unknown
    (e.g. the CPU backend — MFU is then not reported)."""
    return lookup_device_table(device_kind, _PEAK_BF16)


# Parameters that act as one side of a contraction: FLOPs = 2 x
# (output spatial/batch positions) x (param elements). Holds for conv
# (kernel HWIO, grouped or not), depthwise (HW1C), separable (dw + pw
# summed), and dense ((in, out)).
_CONTRACTION_PARAMS = ("kernel", "dw_kernel", "pw_kernel")


def node_flops(
    op: str,
    node_params: dict[str, Any],
    out_shape: Sequence[int],
) -> float:
    """Forward FLOPs of one node given its output shape."""
    import numpy as np

    out_elems = float(np.prod(out_shape)) if out_shape else 1.0
    if op == "dense":
        k = node_params.get("kernel")
        if k is None:
            return out_elems
        in_features = k.shape[0]
        return 2.0 * out_elems * in_features
    if op == "mha" and "wq" in node_params:
        b, s, d = out_shape[-3], out_shape[-2], out_shape[-1]
        return attention_flops(batch=b, seq_len=s, dim=d)
    kernels = [
        node_params[p] for p in _CONTRACTION_PARAMS if p in node_params
    ]
    if kernels and op in ("conv", "depthwise_conv", "separable_conv"):
        out_positions = out_elems / out_shape[-1]
        total = 0.0
        for k in kernels:
            # kernel [kh, kw, cin/groups, cout]: each output position
            # contracts kh*kw*(cin/groups) per channel -> 2 x positions
            # x kernel.size MACs-as-FLOPs.
            total += 2.0 * out_positions * float(k.size)
        return total
    # Everything else (BN folded at inference, activations, pools, adds,
    # softmax) is a small constant per output element.
    return out_elems


def flops_by_node(
    graph: Graph,
    params: GraphParams,
    input_shape: Sequence[int],
    input_dtype: Any = None,
    *,
    specs: Any = None,
) -> dict[str, float]:
    """Per-node forward FLOPs for one input of `input_shape` (batch dim
    included), from the IR's single source of shape truth. `specs`
    short-circuits shape inference when the caller already ran it."""
    import jax.numpy as jnp

    if specs is None:
        specs = graph.infer_shapes(
            params,
            input_shape,
            dtype=jnp.float32 if input_dtype is None else input_dtype,
        )
    return {
        node.name: node_flops(
            node.op, params.get(node.name, {}), specs[node.name].shape
        )
        for node in graph.nodes
    }


def graph_flops(
    graph: Graph, params: GraphParams, input_shape: Sequence[int]
) -> float:
    """Total forward FLOPs for one input of `input_shape`."""
    return sum(flops_by_node(graph, params, input_shape).values())


def balanced_cuts(
    graph: Graph,
    params: GraphParams,
    input_shape: Sequence[int],
    num_stages: int,
    candidates: Sequence[Any] | None = None,
    input_dtype: Any = None,
) -> list[Any]:
    """Pick num_stages-1 boundaries that split the graph into stages of
    near-equal FLOPs (not equal candidate COUNT — the index-even picks
    of Model.default_cuts give ResNet50's early high-resolution convs
    far more work than the tail). Candidates default to
    chain_boundaries(graph); each is scored by the cumulative FLOPs of
    everything at or before its last member, and the picks closest to
    the i/num_stages fractions win (kept strictly increasing).
    """
    from defer_tpu.graph.partition import chain_boundaries

    if num_stages < 1:
        raise ValueError("num_stages must be >= 1")
    if num_stages == 1:
        return []
    if candidates is None:
        candidates = chain_boundaries(graph)
    if num_stages - 1 > len(candidates):
        raise ValueError(
            f"{len(candidates)} candidate boundaries cannot make "
            f"{num_stages} stages"
        )
    per_node = flops_by_node(graph, params, input_shape, input_dtype)
    cum: dict[str, float] = {}
    running = 0.0
    for node in graph.nodes:
        running += per_node[node.name]
        cum[node.name] = running
    total = running

    def score(cand) -> float:
        members = (cand,) if isinstance(cand, str) else cand
        return max(cum[m] for m in members)

    scores = [score(c) for c in candidates]
    picks: list[int] = []
    prev = -1
    remaining = num_stages - 1
    for k in range(1, num_stages):
        target = total * k / num_stages
        # Best candidate for this fraction that still leaves room for
        # the remaining picks and stays after the previous one.
        lo = prev + 1
        hi = len(candidates) - (remaining - len(picks) - 1)
        best = min(
            range(lo, hi), key=lambda i: abs(scores[i] - target)
        )
        picks.append(best)
        prev = best
    return [candidates[i] for i in picks]


def attention_flops(*, batch: int, seq_len: int, dim: int) -> float:
    """One self-attention layer's forward FLOPs (head-count invariant):
    4 QKVO projection matmuls at 2*B*S*D*D each + the two S x S
    contractions (logits, weighted values) at 2*B*S*S*D each. The ONE
    definition shared by per-node accounting (node_flops 'mha') and the
    whole-stack formula (transformer_flops)."""
    tokens = float(batch * seq_len)
    return 2.0 * tokens * (4.0 * dim * dim) + 2.0 * tokens * (
        2.0 * seq_len * dim
    )


def transformer_flops(
    *,
    num_layers: int,
    dim: int,
    ffn_dim: int,
    seq_len: int,
    batch: int,
    vocab_size: int = 0,
    num_experts_active: int = 1,
) -> float:
    """Analytic forward FLOPs for one transformer-encoder microbatch:
    per layer 4 QKVO projections + 2 attention matmuls + 2 FFN matmuls
    (the standard 2*(4*D^2 + 2*S*D)*S*B + 2*2*D*F*S*B accounting)."""
    tokens = float(batch * seq_len)
    per_layer = (
        attention_flops(batch=batch, seq_len=seq_len, dim=dim)
        + 2.0 * tokens * (2.0 * dim * ffn_dim) * num_experts_active
    )
    total = num_layers * per_layer
    if vocab_size:
        total += 2.0 * tokens * dim * vocab_size
    return total
