"""True device synchronization.

On some PJRT transports (e.g. the tunneled single-chip dev setup),
completion *notification* lags actual execution by tens of ms per array:
`block_until_ready()` / `is_ready()` are unreliable or slow to flip,
which silently turns throughput numbers into dispatch-rate numbers — or
throttles a consume loop to the notification latency. Fetching data is
the one fast, honest barrier: a host read of an output element can only
return after its producer ran, so we fetch a single trailing element —
one tiny transfer, not the full output.

Design consequence for hot loops (see Pipeline.stream): never wait
per-item; sync once per window on one array, and retire the whole
prefix — device program order guarantees everything enqueued before the
synced item has also completed.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable

import jax
import numpy as np


def hard_sync(*arrays: Any) -> None:
    """Block until every given value's computation has truly completed
    (fetch one element per leaf as a ground-truth barrier). Accepts
    pytrees — multi-tensor pipeline boundaries pass activation tuples."""
    for arr in jax.tree_util.tree_leaves(arrays):
        if getattr(arr, "ndim", 0) > 0 and arr.size > 1:
            # analysis: ignore[host-sync-in-hot-loop] this IS the
            # sanctioned barrier primitive — hot paths amortize it
            # through Retirer windows (one fetch per window)
            np.asarray(arr.ravel()[-1:])
        else:
            # analysis: ignore[host-sync-in-hot-loop] same: the
            # barrier primitive itself, scalar case
            np.asarray(arr)


# One in-flight fetch per array: a timed-out hard_sync_timeout leaves its
# fetch thread blocked until the array completes; a retry on the same
# array must join that fetch, not spawn another thread doing the same
# device-to-host transfer.
_inflight_lock = threading.Lock()
_inflight: dict[int, threading.Event] = {}


def hard_sync_timeout(arr: jax.Array, timeout_s: float) -> bool:
    """hard_sync with a deadline (the fetch runs in a helper thread).
    Returns False on timeout — the caller decides how to fail. A fetch
    error (e.g. an XLA runtime failure surfacing on the transfer) is
    re-raised here, not swallowed. Used by the streaming drain so a
    stuck stage trips the watchdog instead of hanging the host forever
    (the reference hangs, see reference src/node.py:102-103)."""
    key = id(arr)
    with _inflight_lock:
        done = _inflight.get(key)
        if done is None:
            done = threading.Event()
            done.error = None  # type: ignore[attr-defined]
            _inflight[key] = done

            def fetch() -> None:
                try:
                    hard_sync(arr)
                except BaseException as e:  # noqa: BLE001 — relayed below
                    done.error = e  # type: ignore[attr-defined]
                finally:
                    with _inflight_lock:
                        _inflight.pop(key, None)
                    done.set()

            threading.Thread(target=fetch, daemon=True).start()
    finished = done.wait(timeout_s)
    err = getattr(done, "error", None)
    if finished and err is not None:
        raise err
    return finished


class Retirer:
    """Windowed retire of async results, in order.

    The one implementation of the batched-barrier pattern every hot loop
    here uses (Pipeline.stream, DEFER.run_defer, run_local_inference):
    emit the known-ready prefix for free; under depth pressure take ONE
    barrier on the middle of the window and retire the whole prefix —
    device program order guarantees everything enqueued before the
    synced item has completed (see module docstring). Never wait
    per-item.

    `sync` is the barrier (default `hard_sync`); a caller may supply a
    timeout-aware one (DEFER's watchdog barrier). It must not mutate the
    queue — retirement is identity-based on the synced item, so a
    barrier that covers more (or fewer) items than the caller guessed
    still retires exactly the completed prefix.
    """

    def __init__(
        self,
        depth: int,
        sync: Callable[[Any], None] = hard_sync,
    ):
        self.depth = depth
        self.sync = sync
        self.pending: collections.deque[Any] = collections.deque()
        # Completed results rescued when a barrier raised mid-add —
        # returned by the next collect() instead of being lost.
        self._spill: list[Any] = []

    def __len__(self) -> int:
        return len(self.pending)

    def ready_count(self) -> int:
        """Length of the known-completed prefix (including any
        barrier-failure spill)."""
        n = len(self._spill)
        for item in self.pending:
            if not item.is_ready():
                break
            n += 1
        return n

    def _pop_through(self, target: Any) -> list[Any]:
        out = []
        while self.pending:
            done = self.pending[0] is target
            out.append(self.pending.popleft())
            if done:
                break
        return out

    def add(self, item: Any) -> list[Any]:
        """Enqueue one async result; returns items retired by pressure
        (ready prefix plus, at depth, one batched-barrier prefix)."""
        self.pending.append(item)
        out = self.collect()
        if len(self.pending) >= self.depth:
            target = self.pending[len(self.pending) // 2]
            try:
                self.sync(target)
            except BaseException:
                # The already-collected prefix is COMPLETED work; park
                # it so a recovering caller's next collect() emits it
                # rather than losing it with the raise.
                self._spill = out + self._spill
                raise
            out.extend(self._pop_through(target))
        return out

    def collect(self) -> list[Any]:
        """Retire the known-ready prefix (plus any barrier-failure
        spill) without blocking."""
        out = self._spill
        self._spill = []
        while self.pending and self.pending[0].is_ready():
            out.append(self.pending.popleft())
        return out

    def flush(self) -> list[Any]:
        """Barrier on the newest item and retire everything."""
        if self.pending:
            self.sync(self.pending[-1])
        out = self._spill + list(self.pending)
        self._spill = []
        self.pending.clear()
        return out

    def discard(self) -> int:
        """Drop every pending item WITHOUT syncing; returns the count.

        For failure recovery: in-flight results of a dead pipeline can
        neither complete nor be waited on — the caller re-dispatches
        and accepts the loss (the reference loses the same microbatches
        by hanging forever, reference src/node.py:102-103)."""
        n = len(self.pending)
        self.pending.clear()
        return n
