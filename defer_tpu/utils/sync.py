"""True device synchronization.

On some PJRT transports (e.g. the tunneled single-chip dev setup),
completion *notification* lags actual execution by tens of ms per array:
`block_until_ready()` / `is_ready()` are unreliable or slow to flip,
which silently turns throughput numbers into dispatch-rate numbers — or
throttles a consume loop to the notification latency. Fetching data is
the one fast, honest barrier: a host read of an output element can only
return after its producer ran, so we fetch a single trailing element —
one tiny transfer, not the full output.

Design consequence for hot loops (see Pipeline.stream): never wait
per-item; sync once per window on one array, and retire the whole
prefix — device program order guarantees everything enqueued before the
synced item has also completed.
"""

from __future__ import annotations

import threading

import jax
import numpy as np


def hard_sync(*arrays: jax.Array) -> None:
    """Block until every given array's computation has truly completed
    (fetch one element as a ground-truth barrier)."""
    for arr in arrays:
        if getattr(arr, "ndim", 0) > 0 and arr.size > 1:
            np.asarray(arr.ravel()[-1:])
        else:
            np.asarray(arr)


def hard_sync_timeout(arr: jax.Array, timeout_s: float) -> bool:
    """hard_sync with a deadline (the fetch runs in a helper thread).
    Returns False on timeout — the caller decides how to fail. A fetch
    error (e.g. an XLA runtime failure surfacing on the transfer) is
    re-raised here, not swallowed. Used by the streaming drain so a
    stuck stage trips the watchdog instead of hanging the host forever
    (the reference hangs, see reference src/node.py:102-103)."""
    done = threading.Event()
    error: list[BaseException] = []

    def fetch() -> None:
        try:
            hard_sync(arr)
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            error.append(e)
        finally:
            done.set()

    t = threading.Thread(target=fetch, daemon=True)
    t.start()
    finished = done.wait(timeout_s)
    if finished and error:
        raise error[0]
    return finished
