"""Tracing seam over jax.profiler.

The reference's only observability is `[DEBUG]` prints and wall-clock
throughput counters (reference src/test.py:30-41, SURVEY.md §5). Here
the framework exposes real device traces: `trace(dir)` captures a
TensorBoard-loadable profile, and `annotate(name)` labels host-side
regions (stage dispatch, feed, drain) so pipeline bubbles are visible
against device activity.

Both degrade to no-ops if profiling is unavailable on the platform, so
production paths can call them unconditionally.
"""

from __future__ import annotations

import contextlib
import os

import jax

from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)

# Env var consumed by bench.py and the api stream loop: set to a
# directory to capture a device trace of the benchmark/stream.
TRACE_ENV = "DEFER_TPU_TRACE"


@contextlib.contextmanager
def trace(trace_dir: str | None = None):
    """Capture a jax.profiler trace into `trace_dir` (or $DEFER_TPU_TRACE;
    no-op if neither is set or the profiler fails to start)."""
    target = trace_dir or os.environ.get(TRACE_ENV)
    if not target:
        yield None
        return
    try:
        jax.profiler.start_trace(target)
    except Exception as e:  # profiler can be unsupported per-platform
        log.warning("profiler trace unavailable: %s", e)
        yield None
        return
    try:
        yield target
    finally:
        try:
            jax.profiler.stop_trace()
            log.info("wrote device trace to %s", target)
        except Exception as e:
            log.warning("profiler stop failed: %s", e)


def annotate(name: str):
    """Named host-region annotation visible in captured traces."""
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


class WindowTrace:
    """Trace a bounded window of an unbounded loop.

    An open-ended `trace()` around a serving loop would buffer events
    for the whole process lifetime (multi-GB profiles TensorBoard can't
    load). This starts on the first `tick()` and stops after `limit`
    ticks — or at `close()`, whichever comes first. Inert unless
    $DEFER_TPU_TRACE (or trace_dir) is set.
    """

    def __init__(self, limit: int = 64, trace_dir: str | None = None):
        self.limit = limit
        self.target = trace_dir or os.environ.get(TRACE_ENV)
        self._ticks = 0
        self._cm = None  # the trace() context, entered on first tick
        self._active = False
        self._done = False

    def tick(self) -> None:
        if not self.target or self._done:
            return
        if not self._active:
            self._cm = trace(self.target)
            if self._cm.__enter__() is None:  # profiler unavailable
                self._cm.__exit__(None, None, None)
                self._done = True
                return
            self._active = True
        self._ticks += 1
        if self._ticks >= self.limit:
            self.close()

    def close(self) -> None:
        if self._active:
            self._cm.__exit__(None, None, None)
            self._active = False
        self._done = True
