"""Roofline analysis: per-node arithmetic intensity vs the chip.

MFU says how much of the MXU a model uses; it cannot say WHY the rest
is idle. The roofline model does: each op's arithmetic intensity
(FLOPs per HBM byte moved) against the chip's ridge point
(peak FLOPs / peak bandwidth) classifies it compute-bound (more
intensity than the ridge — the MXU is the limit) or memory-bound (HBM
traffic is the limit, more FLOPs are free). The reference has no
performance analysis at all (throughput-by-wall-clock only, reference
src/test.py:33-41); this is the analysis tool its users would need
next.

Byte accounting has two modes. The unfused mode is the streaming
bound per node in isolation: read every input activation once, read
params once, write the output once. That over-counts what XLA actually
executes — elementwise chains (BN, activations, residual adds, pads)
fuse into their producer's epilogue and never round-trip HBM — so the
default `assume_fusion=True` mode folds fusible ops: a fusible op's
first input arrives in registers from its producer (not read), and its
output is only written when a non-fusible consumer needs it. Neither
mode is a simulator; both are triage signals.
"""

from __future__ import annotations

from typing import Any, Sequence

from defer_tpu.graph.ir import Graph, GraphParams
from defer_tpu.utils.flops import (
    flops_by_node,
    lookup_device_table,
    peak_flops,
)

# Public peak HBM bandwidth figures by device kind, bytes/sec. Order
# matters: specific keys ('v4 lite') before generic ('v4'), mirroring
# flops._PEAK_BF16.
_PEAK_BW: tuple[tuple[str, float], ...] = (
    ("v5 lite", 819e9),  # v5e
    ("v5e", 819e9),
    ("v5p", 2765e9),
    ("v6 lite", 1640e9),  # Trillium
    ("v6e", 1640e9),
    ("v4 lite", 614e9),  # v4i
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def peak_bandwidth(device_kind: str) -> float | None:
    return lookup_device_table(device_kind, _PEAK_BW)


# Ops XLA fuses into a producer's epilogue (elementwise / data
# movement): their primary input never round-trips HBM.
_FUSIBLE = frozenset(
    {
        "relu",
        "relu6",
        "sigmoid",
        "tanh",
        "swish",
        "gelu",
        "softmax",
        "batch_norm",
        "layer_norm",
        "scale",
        "rescale",
        "normalization",
        "identity",
        "dropout",
        "zero_pad",
        "add",
        "multiply",
    }
)


def bytes_by_node(
    graph: Graph,
    params: GraphParams,
    input_shape: Sequence[int],
    input_dtype: Any = None,
    *,
    assume_fusion: bool = True,
    specs: dict | None = None,
) -> dict[str, float]:
    """Per-node HBM bytes from the IR's inferred shapes.

    assume_fusion=False: each node in isolation (inputs + params read,
    output written). assume_fusion=True (default): fusible elementwise
    ops receive their FIRST input in registers and only write their
    output if some consumer is non-fusible (or it is the graph output)
    — the XLA epilogue-fusion picture. `specs` short-circuits shape
    inference when the caller already ran it."""
    import jax.numpy as jnp
    import numpy as np

    if specs is None:
        specs = graph.infer_shapes(
            params,
            input_shape,
            dtype=jnp.float32 if input_dtype is None else input_dtype,
        )
    node_map = graph.node_map
    consumers = graph.consumers()
    out_name = getattr(graph, "output_name", None)
    out_names = set(getattr(graph, "output_names", ()))
    if out_name is not None:
        out_names.add(out_name)

    def nbytes(spec) -> float:
        return float(np.prod(spec.shape)) * spec.dtype.itemsize

    out: dict[str, float] = {}
    for node in graph.nodes:
        if node.op == "input":
            out[node.name] = 0.0
            continue
        fused = assume_fusion and node.op in _FUSIBLE
        total = 0.0
        # Output write: always for non-fused; for fused only when a
        # non-fusible consumer (or the graph output) materializes it.
        if not fused:
            total += nbytes(specs[node.name])
        else:
            cons = consumers.get(node.name, [])
            # A consumer keeps this value in registers only when it is
            # itself fusible AND takes it as its first input.
            needs_write = node.name in out_names or any(
                node_map[c].op not in _FUSIBLE
                or node_map[c].inputs[0] != node.name
                for c in cons
            )
            if needs_write:
                total += nbytes(specs[node.name])
        for i, inp in enumerate(node.inputs):
            if fused and i == 0 and node_map[inp].op != "input":
                # Arrives in registers from a computing producer; the
                # graph INPUT has no producer — it always streams from
                # HBM and must be counted.
                continue
            total += nbytes(specs[inp])
        for arr in params.get(node.name, {}).values():
            total += float(arr.size) * arr.dtype.itemsize
        out[node.name] = total
    return out


def roofline_report(
    graph: Graph,
    params: GraphParams,
    input_shape: Sequence[int],
    device_kind: str,
    *,
    input_dtype: Any = None,
    top: int = 8,
    assume_fusion: bool = True,
) -> dict:
    """Classify every node against the chip's ridge point.

    Returns a dict with totals, the predicted time lower bound per
    node (max of compute time and memory time — the roofline), the
    model-level bound, and the `top` heaviest nodes by predicted time.
    """
    import jax.numpy as jnp

    specs = graph.infer_shapes(
        params,
        input_shape,
        dtype=jnp.float32 if input_dtype is None else input_dtype,
    )
    flops = flops_by_node(graph, params, input_shape, specs=specs)
    bts = bytes_by_node(
        graph,
        params,
        input_shape,
        assume_fusion=assume_fusion,
        specs=specs,
    )
    pf = peak_flops(device_kind)
    bw = peak_bandwidth(device_kind)
    ridge = (pf / bw) if pf and bw else None

    nodes = []
    for node in graph.nodes:
        if node.op == "input":
            continue
        f, b = flops[node.name], bts[node.name]
        intensity = f / b if b else float("inf")
        entry = {
            "name": node.name,
            "op": node.op,
            "flops": f,
            "bytes": b,
            "intensity": round(intensity, 2),
        }
        if ridge is not None:
            t_compute = f / pf
            t_memory = b / bw
            entry["bound"] = (
                "compute" if t_compute >= t_memory else "memory"
            )
            entry["t_lower_s"] = max(t_compute, t_memory)
        nodes.append(entry)

    report: dict = {
        "device_kind": device_kind,
        "peak_flops": pf,
        "peak_bandwidth": bw,
        "ridge_intensity": round(ridge, 1) if ridge is not None else None,
        "total_flops": sum(flops.values()),
        "total_bytes": sum(bts.values()),
    }
    if ridge is not None:
        t_total = sum(e["t_lower_s"] for e in nodes)
        by_bound = {"compute": 0.0, "memory": 0.0}
        for e in nodes:
            by_bound[e["bound"]] += e["t_lower_s"]
        report.update(
            {
                "t_lower_s": t_total,
                # Throughput AT this traffic model's bound — not a hard
                # ceiling: real XLA fusion (VMEM reuse across non-
                # elementwise ops, conv input re-use) can move fewer
                # bytes than the model and measure faster.
                "items_per_sec_at_bound": (
                    input_shape[0] / t_total if t_total else None
                ),
                "time_share": {
                    k: round(v / t_total, 3) if t_total else None
                    for k, v in by_bound.items()
                },
                "top_nodes": sorted(
                    nodes, key=lambda e: -e["t_lower_s"]
                )[:top],
            }
        )
    else:
        report["top_nodes"] = sorted(nodes, key=lambda e: -e["flops"])[:top]
    return report


def format_report(report: dict) -> str:
    """Human-readable summary of roofline_report."""
    lines = [
        f"roofline[{report['device_kind']}]: "
        f"{report['total_flops'] / 1e9:.2f} GFLOP, "
        f"{report['total_bytes'] / 1e6:.1f} MB moved"
        + (
            f", ridge {report['ridge_intensity']} FLOP/B"
            if report.get("ridge_intensity")
            else ""
        )
    ]
    if "t_lower_s" in report:
        share = report["time_share"]
        lines.append(
            f"  bound: {share['compute']:.0%} compute / "
            f"{share['memory']:.0%} memory; "
            f"{report['items_per_sec_at_bound']:.0f} items/s at the "
            "traffic-model bound"
        )
    for e in report["top_nodes"]:
        t = (
            f" {e['t_lower_s'] * 1e6:.0f}us ({e['bound']})"
            if "t_lower_s" in e
            else ""
        )
        lines.append(
            f"  {e['name']:<28} {e['op']:<16} "
            f"{e['flops'] / 1e6:>9.1f} MFLOP {e['intensity']:>8.1f} F/B{t}"
        )
    return "\n".join(lines)
