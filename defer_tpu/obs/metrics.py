"""Zero-dependency metrics core: Counter / Gauge / Histogram behind a
process-global, resettable MetricsRegistry.

The reference's only runtime observability is `[DEBUG]` prints and a
wall-clock throughput counter (reference src/test.py:33-41); our own
`utils/profiling.py` captures device *traces* but counts nothing. This
module is the missing *metrics* layer: the serving and pipeline
runtimes increment always-on instruments, and export sinks
(`obs/export.py`) read them on demand — nothing is paid per sample
beyond an int add under a lock, so instrumentation stays wired into
the hot paths unconditionally.

Design constraints, in order:

  * **Hot-path cost**: instrument handles are resolved ONCE (at server
    / gatherer construction) and cached; a per-token event is then a
    lock acquire + int add, no allocation. Histograms use FIXED
    log-spaced bucket edges found by `bisect` (C implemented), so
    observing never allocates either.
  * **Thread safety**: the decode servers, `runtime/batching.py`, and
    the transport relay all touch metrics from worker threads; every
    mutation takes the instrument's own lock (int += under the GIL is
    NOT atomic — it is a load/add/store that can interleave).
  * **Resettable, never replaced**: `reset()` zeroes every instrument
    IN PLACE rather than swapping the registry object, so handles
    cached by live servers/transports stay valid across test
    boundaries. There is deliberately no `set_registry` — a swapped
    registry would silently orphan every cached handle.
"""

from __future__ import annotations

import bisect
import contextlib
import threading
from typing import Any


def log_buckets(
    start: float = 1e-4, factor: float = 2.0, count: int = 20
) -> tuple[float, ...]:
    """Fixed log-spaced histogram edges: start * factor**i. The
    default (0.1 ms .. ~52 s, x2) covers queue waits, TTFT, and
    inter-token latency on anything from a CPU test to a loaded TPU
    server without per-workload tuning."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError(
            f"need start > 0, factor > 1, count >= 1; got "
            f"{start}/{factor}/{count}"
        )
    return tuple(start * factor**i for i in range(count))


def _label_key(labels: dict[str, str] | None) -> tuple:
    return tuple(sorted((labels or {}).items()))


class Counter:
    """Monotonically increasing count (Prometheus counter)."""

    __slots__ = ("name", "help", "labels", "_lock", "_value")

    def __init__(
        self, name: str, help: str = "", labels: dict | None = None
    ):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int | float = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({n})")
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0

    def _snapshot(self):
        return self._value


class Gauge:
    """Point-in-time value that can go both ways (pool occupancy,
    per-stage step time)."""

    __slots__ = ("name", "help", "labels", "_lock", "_value")

    def __init__(
        self, name: str, help: str = "", labels: dict | None = None
    ):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n=1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0

    def _snapshot(self):
        return self._value


class Histogram:
    """Fixed-bucket histogram (Prometheus semantics: bucket i counts
    observations <= edges[i], plus an implicit +Inf overflow bucket).

    Edges are fixed at construction — log-spaced by default — so
    `observe` is one bisect + three int/float adds under the lock:
    no per-sample allocation, ever."""

    __slots__ = (
        "name", "help", "labels", "edges", "_lock", "_counts",
        "_sum", "_count",
    )

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: tuple | list | None = None,
        labels: dict | None = None,
    ):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        edges = tuple(buckets) if buckets is not None else log_buckets()
        if not edges or list(edges) != sorted(edges):
            raise ValueError(
                f"histogram {name} needs ascending non-empty edges"
            )
        self.edges = edges
        self._lock = threading.Lock()
        self._counts = [0] * (len(edges) + 1)  # [..., +Inf]
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float, n: int = 1) -> None:
        """Record `v` (n times — one bisect either way; servers use
        n = active slots for the shared tick-to-tick latency)."""
        i = bisect.bisect_left(self.edges, v)
        with self._lock:
            self._counts[i] += n
            self._count += n
            self._sum += v * n

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.edges) + 1)
            self._sum = 0.0
            self._count = 0

    def _snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum = 0
        buckets = []
        for edge, c in zip(self.edges, counts):
            cum += c
            buckets.append([edge, cum])
        buckets.append(["+Inf", total])
        return {"count": total, "sum": s, "buckets": buckets}

    def approx_quantile(self, q: float) -> float | None:
        """Bucket-interpolated quantile estimate (None when empty) —
        good enough for a bench headline, not for SLO accounting."""
        if not 0 <= q <= 1:
            raise ValueError(f"quantile {q} not in [0, 1]")
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return None
        target = q * total
        cum = 0
        lo = 0.0
        for i, c in enumerate(counts):
            if cum + c >= target and c:
                hi = (
                    self.edges[i]
                    if i < len(self.edges)
                    else self.edges[-1]
                )
                frac = (target - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
            lo = self.edges[i] if i < len(self.edges) else self.edges[-1]
        return self.edges[-1]


class MetricsRegistry:
    """Get-or-create instrument store. Keyed by (name, labels): two
    call sites asking for the same name+labels share the instrument
    (that is how the flat and paged servers aggregate, and how a
    re-constructed server resumes its counters)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict[tuple, Any] = {}

    def _get(self, cls, name, help, labels, **kw):
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help, labels=labels, **kw)
                self._metrics[key] = m
            elif type(m) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(
        self, name: str, help: str = "", labels: dict | None = None
    ) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(
        self, name: str, help: str = "", labels: dict | None = None
    ) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: tuple | list | None = None,
        labels: dict | None = None,
    ) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def __iter__(self):
        with self._lock:
            return iter(list(self._metrics.values()))

    def __len__(self) -> int:
        return len(self._metrics)

    def reset(self) -> None:
        """Zero every instrument IN PLACE. Cached handles stay valid —
        the test-isolation contract (a server built in one test keeps
        working after another test resets)."""
        for m in self:
            m._reset()

    def value(self, name: str, **labels):
        """Convenience read: the instrument's current value (counters
        and gauges) or snapshot dict (histograms); None if absent."""
        m = self._metrics.get((name, _label_key(labels)))
        return None if m is None else m._snapshot()

    def to_dict(self) -> dict:
        """JSON-ready snapshot: {"counters": {...}, "gauges": {...},
        "histograms": {...}} keyed by the Prometheus sample name
        (labels rendered inline, sorted)."""
        from defer_tpu.obs.export import sample_name

        out = {"counters": {}, "gauges": {}, "histograms": {}}
        kind = {Counter: "counters", Gauge: "gauges", Histogram: "histograms"}
        for m in self:
            out[kind[type(m)]][sample_name(m.name, m.labels)] = (
                m._snapshot()
            )
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        from defer_tpu.obs.export import prometheus_text

        return prometheus_text(self)


@contextlib.contextmanager
def counter_deltas(registry: MetricsRegistry | None = None):
    """Counter INCREMENTS across a with-block, as
    {prometheus sample name: delta}.

    The registry is process-global and cumulative (reset() exists for
    test isolation, but resetting mid-flight would zero instruments a
    live server is still driving), so "how much did THIS run read?"
    needs a before/after diff. Yields a dict that is empty inside the
    block and populated on exit with every counter whose value grew —
    counters created during the block diff against a baseline of 0.

        with counter_deltas() as d:
            serve_paged(...)
        d['defer_kv_rows_read_total{server="paged"}']
    """
    from defer_tpu.obs.export import sample_name

    reg = registry if registry is not None else _REGISTRY
    before = {
        (m.name, _label_key(m.labels)): m._snapshot()
        for m in reg
        if isinstance(m, Counter)
    }
    out: dict[str, float] = {}
    yield out
    for m in reg:
        if not isinstance(m, Counter):
            continue
        d = m._snapshot() - before.get((m.name, _label_key(m.labels)), 0)
        if d:
            out[sample_name(m.name, m.labels)] = d


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """THE process registry. Intentionally a singleton accessor with no
    setter: hot paths cache handles out of it, and `reset()` zeroes in
    place so those handles survive (see module docstring). Tests that
    need a private registry construct MetricsRegistry() directly."""
    return _REGISTRY


def reset() -> None:
    """Zero the process registry in place (test isolation)."""
    _REGISTRY.reset()
