"""Serving-layer metric handles and the structured stats snapshot.

`ServingMetrics` resolves every instrument the decode servers emit
ONCE, at server construction, against the process registry — the
per-token hot path then touches pre-bound attributes only (lock + int
add, no registry lookup, no allocation). Both servers share metric
names and differ by the `server` label ("flat" | "paged"), so fleet
dashboards aggregate across them for free.

`ServerStats` is the one structured return-channel `serve_greedy` /
`serve_paged` / bench.py report through. It subclasses dict so every
existing `stats["ticks"]` call site keeps working, and adds attribute
access plus the registry snapshot under `stats.metrics`.
"""

from __future__ import annotations

from typing import Any

from defer_tpu.obs.metrics import MetricsRegistry, get_registry

# Latency edges: 0.1 ms .. ~1.6 s (x2). Decode ticks on the CPU test
# rig land mid-range; queue waits under load reach the top.
_LATENCY_BUCKETS = tuple(1e-4 * 2.0**i for i in range(15))


class ServingMetrics:
    """Pre-bound instrument handles for one decode server flavour."""

    def __init__(
        self,
        server: str,
        registry: MetricsRegistry | None = None,
        mesh_shape: str | None = None,
    ):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        labels = {"server": server}
        # Topology-auditable instruments additionally carry the mesh
        # shape (e.g. "model=4") when the server runs tensor-parallel,
        # so per-shard dispatch/bandwidth claims are separable from the
        # single-device series; mesh_shape=None keeps the label set —
        # and thus the exposition identity — exactly as before.
        mesh_labels = dict(labels)
        if mesh_shape is not None:
            mesh_labels["mesh"] = mesh_shape
        self.requests_admitted = reg.counter(
            "defer_requests_admitted_total",
            "Requests admitted into a decode slot", labels,
        )
        self.requests_finished = reg.counter(
            "defer_requests_finished_total",
            "Requests that finished decoding", labels,
        )
        self.ticks = reg.counter(
            "defer_decode_ticks_total",
            "Batched decode steps executed", labels,
        )
        self.tokens_generated = reg.counter(
            "defer_tokens_generated_total",
            "Tokens emitted by decode slots (incl. first token)", labels,
        )
        self.prefill_tokens = reg.counter(
            "defer_prefill_tokens_total",
            "Prompt tokens run through prefill", labels,
        )
        self.ttft = reg.histogram(
            "defer_ttft_seconds",
            "Admission to first-token dispatch (host-side; the token "
            "array may still be in flight on device)",
            _LATENCY_BUCKETS, labels,
        )
        self.itl = reg.histogram(
            "defer_itl_seconds",
            "Inter-token latency: host wall time between decode ticks, "
            "weighted by active slots",
            _LATENCY_BUCKETS, labels,
        )
        self.queue_wait = reg.histogram(
            "defer_queue_wait_seconds",
            "submit() to admission", _LATENCY_BUCKETS, labels,
        )
        # Paged-only pool/cache instruments; registered for both
        # flavours (flat just leaves them at zero) so exposition shape
        # does not depend on which server ran first.
        self.pool_blocks_free = reg.gauge(
            "defer_pool_blocks_free", "KV pool blocks on the free list",
            labels,
        )
        self.pool_blocks_used = reg.gauge(
            "defer_pool_blocks_used", "KV pool blocks held by slots",
            labels,
        )
        self.prefix_hits = reg.counter(
            "defer_prefix_cache_hits_total",
            "Prompt blocks served from the radix cache", labels,
        )
        self.prefix_misses = reg.counter(
            "defer_prefix_cache_misses_total",
            "Full prompt blocks that had to be prefilled", labels,
        )
        self.prefix_evictions = reg.counter(
            "defer_prefix_cache_evictions_total",
            "Parked cache blocks reclaimed under pool pressure", labels,
        )
        self.prefix_parks = reg.counter(
            "defer_prefix_cache_parks_total",
            "Cache blocks parked at refcount zero (LRU candidates)",
            labels,
        )
        self.prefix_revivals = reg.counter(
            "defer_prefix_cache_revivals_total",
            "Parked cache blocks revived by a new sharer", labels,
        )
        # KV-pool storage + host-RAM spill tier (runtime/paged.py
        # kv_dtype= / spill_bytes=). kv_pool_bytes is the pool's
        # RESIDENCY footprint — int8 pools read ~0.5x an fp pool plus
        # scale overhead — while the row counters above stay dtype-
        # agnostic (a row is a token position whatever its byte
        # width). spill_bytes is a gauge: the store's current
        # occupancy, trimmed oldest-first against its cap.
        self.kv_pool_bytes = reg.gauge(
            "defer_kv_pool_bytes",
            "Total bytes of the paged KV pool as allocated (K + V "
            "payloads plus int8 block scales when kv_dtype='int8')",
            labels,
        )
        self.prefix_spilled = reg.counter(
            "defer_prefix_spilled_total",
            "Evicted prefix blocks drained into the host-RAM spill "
            "store", labels,
        )
        self.prefix_spill_hits = reg.counter(
            "defer_prefix_spill_hits_total",
            "Radix walk misses served from the spill store (block "
            "revived into the pool instead of re-prefilled)", labels,
        )
        self.spill_bytes = reg.gauge(
            "defer_prefix_spill_bytes",
            "Current bytes resident in the host-RAM spill store",
            labels,
        )
        # Block-native attention accounting (runtime/paged.py): rows
        # the tick's attention path actually read vs what the gathered
        # full-pool-view path reads regardless of depth. One unit =
        # one K/V row pair (token position) for one slot for one tick,
        # layer/head-agnostic — multiply by 2 * L * Hkv * Dh * itemsize
        # for bytes. The ratio read/baseline is the bandwidth win.
        self.kv_rows_read = reg.counter(
            "defer_kv_rows_read_total",
            "KV cache rows (token positions, K+V pair = 1 unit, "
            "layer-agnostic) read by decode-tick attention, summed "
            "over slots; PER-SHARD under a mesh (each shard holds "
            "kv_heads/TP heads, so reads scale as 1/TP)", mesh_labels,
        )
        self.kv_rows_gathered = reg.counter(
            "defer_kv_rows_gathered_baseline_total",
            "Rows the gathered full-pool-view path would have read "
            "for the same ticks (B * max_blocks * block_size each)",
            labels,
        )
        self.kv_rows_last = reg.gauge(
            "defer_kv_rows_read_last_tick",
            "KV rows read by the most recent decode tick", labels,
        )
        # Dispatch-efficiency instruments (fused decode windows,
        # runtime/*.py `decode_window`): one host dispatch drives up
        # to K decode sub-steps, so dispatches-per-token falls toward
        # 1/K while tokens_per_dispatch rises toward K * active slots.
        # At decode_window=1 host_dispatches == decode_ticks and the
        # gauge reads the active-slot count.
        self.host_dispatches = reg.counter(
            "defer_host_dispatches_total",
            "Decode-loop host dispatches (one per window; equals "
            "decode ticks at decode_window=1). Unchanged by tensor "
            "parallelism — one dispatch drives all shards",
            mesh_labels,
        )
        self.tp_psums = reg.counter(
            "defer_tp_psum_total",
            "Cross-shard collectives issued by sharded tick bodies "
            "(2 per layer + embed psum + logits all-gather per "
            "forward); zero on mesh=None", mesh_labels,
        )
        self.tokens_per_dispatch = reg.gauge(
            "defer_tokens_per_dispatch",
            "Tokens accepted from the most recent decode dispatch",
            labels,
        )
        self.window_truncated = reg.counter(
            "defer_window_truncated_total",
            "Decode windows a slot cut short (eos froze the row "
            "on-device, or a stop sequence discarded the tail on "
            "drain)", labels,
        )
        # Continuous-batching interference (runtime/schedule.py +
        # runtime/paged.py `prefill_budget=`): how much decode time
        # admission prefill steals. In the serialized stall path every
        # prefill dispatch issued while a decode slot is live is a
        # stall tick; mixed-mode ticks carry prompt chunks inside the
        # decode dispatch instead, so stall ticks stay 0 and the
        # fraction gauge reads ~0.
        self.prefill_stall_ticks = reg.counter(
            "defer_prefill_stall_ticks_total",
            "Admission-prefill dispatches issued while at least one "
            "decode slot sat stalled waiting for the tick loop "
            "(serialized-prefill interference; 0 under "
            "prefill_budget=)", labels,
        )
        self.mixed_prefill_tokens = reg.counter(
            "defer_mixed_prefill_tokens_total",
            "Prompt tokens carried by fused mixed decode+prefill "
            "dispatches (prefill_budget= ticks)", labels,
        )
        self.decode_stall_fraction = reg.gauge(
            "defer_decode_stall_fraction",
            "Fraction of decode-capable dispatch slots spent stalled "
            "behind admission prefill: stall_ticks / (decode_ticks + "
            "stall_ticks)", labels,
        )
        # Speculative decoding (models/speculative.py solo loop and
        # runtime/paged.py paged serving both report through these).
        # acceptance = accepted/proposed is the one-number health
        # signal: the target-dispatch amortization k-token speculation
        # buys is (1 + acceptance * k) tokens per verify forward.
        self.spec_proposed = reg.counter(
            "defer_spec_proposed_total",
            "Draft tokens proposed to a target verify forward", labels,
        )
        self.spec_accepted = reg.counter(
            "defer_spec_accepted_total",
            "Proposed draft tokens the target accepted", labels,
        )
        self.spec_rounds = reg.counter(
            "defer_spec_rounds_total",
            "Speculative propose/verify rounds executed", labels,
        )
        self.spec_draft_tokens = reg.counter(
            "defer_spec_draft_tokens_total",
            "Tokens the DRAFT model computed forwards for (catch-up "
            "feeds + proposal scan steps) — the speculation overhead "
            "side of the acceptance-vs-speedup frontier", labels,
        )
        # Per-round accepted-length distribution: one observation per
        # greedy slot per round, value = draft tokens accepted in
        # [0, k]. Integer-edge buckets make `le="a"` read "rounds that
        # accepted <= a proposals"; the running mean (sum/count) is
        # the old gauge's acceptance*k. Edges cover k <= 16; larger k
        # folds into +Inf, still mean-exact.
        self.spec_acceptance = reg.histogram(
            "defer_spec_acceptance",
            "Accepted draft tokens per speculative round per slot "
            "(distribution; mean = acceptance * spec_k)",
            tuple(float(b) for b in range(17)),
            labels,
        )
        # Constrained decoding (defer_tpu/constrain/): tokens emitted
        # under a DFA mask, and how much of the vocabulary that mask
        # removed per token — masked_frac near 1.0 means the grammar
        # is doing almost all the choosing (JSON punctuation states),
        # near 0.0 means the constraint is along for the ride.
        self.constrained_tokens = reg.counter(
            "defer_constrained_tokens_total",
            "Tokens emitted by slots decoding under a constraint DFA "
            "mask (defer_tpu/constrain/)", labels,
        )
        self.constrain_masked_frac = reg.histogram(
            "defer_constrain_masked_frac",
            "Per-token fraction of the vocabulary the constraint "
            "mask removed (1.0 = grammar-forced, 0.0 = free)",
            tuple(i / 10.0 for i in range(1, 11)),
            labels,
        )
        self.constrain_dead_ends = reg.counter(
            "defer_constrain_dead_ends_total",
            "Requests failed because their (hand-built) constraint "
            "DFA reached a state admitting no token — compiled DFAs "
            "are dead-end-free by construction", labels,
        )
        # Pipeline-parallel serving (runtime/paged.py pp_stages=):
        # schedule-level health of the staged decode loop. Bubble is
        # 1 - mean stage occupancy over the realized dispatch
        # schedule (fill/drain slots plus any group that froze
        # mid-window), NOT the closed-form (S-1)/(S-1+M*W). The
        # per-stage instruments live behind bind_pp() because their
        # label set depends on the stage count.
        self.pp_bubble_fraction = reg.gauge(
            "defer_pp_bubble_fraction",
            "1 - mean stage occupancy of the most recent pipelined "
            "decode window (0 on pp_stages=1 servers)", labels,
        )
        self.pp_inflight = reg.gauge(
            "defer_pp_inflight_microbatches",
            "Microbatch slot groups in flight through the stage "
            "chain (M; 0 on pp_stages=1 servers)", labels,
        )
        self.pp_stage_occupancy: list = []
        self.pp_stage_dispatches: list = []

    def bind_pp(self, num_stages: int) -> None:
        """Resolve the per-stage pipeline instruments (stage-labeled,
        so the label set depends on the server's stage count — the
        FleetMetrics per-replica idiom). Idempotent: the registry
        get-or-creates, so two servers with the same stage count share
        handles."""
        reg = self.registry
        per = [{"stage": str(s)} for s in range(num_stages)]
        self.pp_stage_occupancy = [
            reg.gauge(
                "defer_pp_stage_occupancy",
                "Fraction of the realized window schedule's dispatch "
                "slots this stage spent busy (per stage)",
                lab,
            )
            for lab in per
        ]
        self.pp_stage_dispatches = [
            reg.counter(
                "defer_pp_stage_dispatches_total",
                "Stage-step dispatches issued to this pipeline stage "
                "(one per microbatch per decode round)",
                lab,
            )
            for lab in per
        ]


class DisaggMetrics:
    """Pre-bound instruments for one disaggregated-serving role.

    Both halves of a prefill/decode split emit the same names and
    differ by the `role` label ("prefill" | "decode"), mirroring the
    `server` label convention above. Byte counters count WIRE bytes
    (transport header + codec frame), so sent and recv agree exactly
    on a lossless link and the sent/raw ratio prices the quantized
    transfer mode."""

    def __init__(self, role: str, registry: MetricsRegistry | None = None):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        labels = {"role": role}
        self.kv_blocks_shipped = reg.counter(
            "defer_kv_blocks_shipped_total",
            "Finished KV pool blocks framed onto the wire (full blocks "
            "plus at most one tail block per request)", labels,
        )
        self.kv_bytes_sent = reg.counter(
            "defer_kv_block_bytes_sent_total",
            "Wire bytes of KV-block payload frames sent", labels,
        )
        self.kv_bytes_recv = reg.counter(
            "defer_kv_block_bytes_recv_total",
            "Wire bytes of KV-block payload frames received", labels,
        )
        self.ingest_wait = reg.histogram(
            "defer_kv_ingest_wait_seconds",
            "Received KV payload parked in the ingest queue before the "
            "decode server admitted it", _LATENCY_BUCKETS, labels,
        )
        self.worker_restarts = reg.counter(
            "defer_disagg_worker_restarts_total",
            "Prefill worker sessions restarted after a mid-stream "
            "transport failure", labels,
        )


class FleetMetrics:
    """Pre-bound instruments for the fleet front-end
    (defer_tpu/fleet/). One process-wide set of fleet instruments; the
    per-replica signals (queue depth/wait, in-flight slots, pool
    headroom) carry a `replica` label because every replica's OWN
    `ServingMetrics("paged")` resolves to the same shared instruments
    — per-replica load must be separable for the router to read it."""

    ROUTE_REASONS = ("prefix", "migrate", "load", "fallback")
    SHED_REASONS = ("queue_full", "slo")

    def __init__(
        self, n_replicas: int, registry: MetricsRegistry | None = None
    ):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self.n_replicas = n_replicas
        self.routed = {
            reason: reg.counter(
                "defer_fleet_routed_total",
                "Requests routed to a replica, by routing reason "
                "(prefix = deepest resident prefix; migrate = prefix "
                "holder overloaded, blocks shipped to the target; "
                "load = no resident prefix anywhere, least-loaded; "
                "fallback = prefix existed but was unusable — holder "
                "dead or migration failed)",
                {"reason": reason},
            )
            for reason in self.ROUTE_REASONS
        }
        self.shed = {
            reason: reg.counter(
                "defer_fleet_shed_total",
                "Requests rejected by admission control, by reason "
                "(queue_full = bounded queue never drained within the "
                "deadline; slo = rolling queue-wait p99 already above "
                "the configured SLO)",
                {"reason": reason},
            )
            for reason in self.SHED_REASONS
        }
        self.migrated_blocks = reg.counter(
            "defer_fleet_migrated_blocks_total",
            "Prefix KV blocks shipped between replica pools instead "
            "of being re-prefilled",
        )
        self.advert_age = reg.gauge(
            "defer_fleet_digest_advert_age_seconds",
            "Age of the OLDEST replica digest advertisement at the "
            "most recent routing decision — how stale the prefix "
            "signal can be",
        )
        per = [{"replica": str(i)} for i in range(n_replicas)]
        self.queue_wait = [
            reg.histogram(
                "defer_fleet_queue_wait_seconds",
                "Admission enqueue to replica pickup, per replica",
                _LATENCY_BUCKETS, lab,
            )
            for lab in per
        ]
        self.queue_depth = [
            reg.gauge(
                "defer_fleet_queue_depth",
                "Requests waiting in a replica's admission queue",
                lab,
            )
            for lab in per
        ]
        self.inflight = [
            reg.gauge(
                "defer_fleet_inflight_requests",
                "Requests seated or pending inside a replica's server",
                lab,
            )
            for lab in per
        ]
        self.pool_free = [
            reg.gauge(
                "defer_fleet_pool_blocks_free",
                "Replica KV pool headroom (free-list blocks)",
                lab,
            )
            for lab in per
        ]


class ServerStats(dict):
    """Dict-compatible structured stats snapshot.

    Existing call sites index it (`stats["ticks"]`, `**stats`); new
    code reads attributes (`stats.ticks`, `stats.metrics`). The
    `metrics` key holds `registry.to_dict()` at snapshot time."""

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    @classmethod
    def snapshot(
        cls, registry: MetricsRegistry | None = None, **fields
    ) -> "ServerStats":
        reg = registry if registry is not None else get_registry()
        out = cls(fields)
        out["metrics"] = reg.to_dict()
        return out


class FleetStats(ServerStats):
    """ServerStats for a fleet run: the fleet-level snapshot (routing
    reasons, shed counts, migration totals) plus `replicas`, a list of
    per-replica ServerStats in replica-index order."""
