"""defer_tpu.obs — metrics & telemetry for the serving/pipeline runtimes.

Split from `utils/profiling.py` on purpose: profiling captures device
*traces* (one-shot, heavyweight, opt-in), obs counts and times
*always-on* host-side events (near-free per sample, pull-based export).
See ARCHITECTURE.md "Observability".
"""

from defer_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter_deltas,
    get_registry,
    log_buckets,
    reset,
)
from defer_tpu.obs.export import PeriodicDumper, prometheus_text
from defer_tpu.obs.serving import (
    DisaggMetrics,
    FleetMetrics,
    FleetStats,
    ServerStats,
    ServingMetrics,
)

__all__ = [
    "Counter",
    "DisaggMetrics",
    "FleetMetrics",
    "FleetStats",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "PeriodicDumper",
    "ServerStats",
    "ServingMetrics",
    "counter_deltas",
    "get_registry",
    "log_buckets",
    "prometheus_text",
    "reset",
]
