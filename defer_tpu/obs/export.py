"""Export sinks for the metrics registry.

Three ways out, all pull-based — the hot paths never format anything:

  * `prometheus_text(registry)` — text exposition format 0.0.4, the
    thing a Prometheus scrape endpoint would serve.
  * `MetricsRegistry.to_dict()` (in obs/metrics.py) — JSON-ready
    snapshot for bench.py's JSON-line protocol.
  * `PeriodicDumper` — a daemon thread that dumps one of the above to
    a logger or file every N seconds, for headless runs with no
    scraper attached.
"""

from __future__ import annotations

import json
import threading
import time

from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _escape(v: str) -> str:
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _render_labels(labels: dict, extra: dict | None = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(items.items())
    )
    return "{" + body + "}"


def sample_name(name: str, labels: dict, extra: dict | None = None) -> str:
    return name + _render_labels(labels, extra)


def _fmt(v) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def prometheus_text(registry) -> str:
    """Render every instrument in Prometheus text exposition format.

    Deterministic output: instruments sorted by (name, labels), one
    HELP/TYPE header per metric name, histogram buckets cumulative
    with a trailing +Inf — so a golden-string test pins the format."""
    from defer_tpu.obs.metrics import Counter, Gauge, Histogram

    metrics = sorted(
        registry, key=lambda m: (m.name, sorted(m.labels.items()))
    )
    lines: list[str] = []
    seen_header: set[str] = set()
    for m in metrics:
        if m.name not in seen_header:
            seen_header.add(m.name)
            if m.help:
                lines.append(f"# HELP {m.name} {_escape(m.help)}")
            kind = {
                Counter: "counter", Gauge: "gauge", Histogram: "histogram"
            }[type(m)]
            lines.append(f"# TYPE {m.name} {kind}")
        if isinstance(m, Histogram):
            snap = m._snapshot()
            for le, cum in snap["buckets"]:
                le_s = le if le == "+Inf" else _fmt(le)
                lines.append(
                    f"{m.name}_bucket"
                    f"{_render_labels(m.labels, {'le': le_s})} {cum}"
                )
            lines.append(
                f"{m.name}_sum{_render_labels(m.labels)} "
                f"{_fmt(snap['sum'])}"
            )
            lines.append(
                f"{m.name}_count{_render_labels(m.labels)} "
                f"{snap['count']}"
            )
        else:
            lines.append(
                f"{sample_name(m.name, m.labels)} {_fmt(m._snapshot())}"
            )
    return "\n".join(lines) + ("\n" if lines else "")


class PeriodicDumper:
    """Daemon thread that snapshots the registry every `interval_s`
    and writes it to a file (`path`) or the module logger. The thread
    only ever *reads* instruments, so a dumper costs the hot paths
    nothing; `fmt` is "json" or "prometheus"."""

    def __init__(
        self,
        registry,
        interval_s: float = 10.0,
        path: str | None = None,
        fmt: str = "json",
    ):
        if fmt not in ("json", "prometheus"):
            raise ValueError(f"fmt must be json|prometheus, got {fmt!r}")
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.registry = registry
        self.interval_s = interval_s
        self.path = path
        self.fmt = fmt
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _render(self) -> str:
        if self.fmt == "prometheus":
            return self.registry.to_prometheus()
        return json.dumps(self.registry.to_dict(), sort_keys=True)

    def dump_once(self) -> str:
        text = self._render()
        if self.path:
            with open(self.path, "a") as f:
                f.write(text if text.endswith("\n") else text + "\n")
        else:
            log.info("metrics: %s", text)
        return text

    # analysis: domain(transport) periodic exposition writes leave the process; server state is only read
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.dump_once()
            except Exception:  # a broken sink must not kill the server
                log.exception("metrics dump failed")

    def start(self) -> "PeriodicDumper":
        if self._thread is not None:
            raise RuntimeError("dumper already started")
        self._thread = threading.Thread(
            target=self._run, name="obs-dumper", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, final_dump: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_dump:
            self.dump_once()

    def __enter__(self) -> "PeriodicDumper":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(final_dump=not any(exc))
