"""Standard CNN/elementwise op library.

Layout is NHWC with HWIO conv kernels — the TPU-native layout (channels
on the 128-wide lane dimension). All shape math lives in the `apply`
functions; the IR derives shapes from them via `jax.eval_shape`
(defer_tpu/graph/ir.py), so there is one source of truth.

Covers every layer kind used by the reference's model zoo
(BASELINE.json configs: ResNet50, VGG19, InceptionV3, MobileNetV2,
EfficientNet-B0, InceptionResNetV2, NASNet) — conv/depthwise/dense/BN,
poolings, pad/crop, add/mul/concat, and the activation set.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from defer_tpu.ops.registry import register_op


def _pair(v: Any) -> tuple[int, int]:
    if isinstance(v, (tuple, list)):
        a, b = v
        return (int(a), int(b))
    return (int(v), int(v))


def _conv_padding(
    padding: Any, kernel: tuple[int, int], dilation: tuple[int, int]
) -> Any:
    """Resolve a padding attr to something lax.conv accepts."""
    if isinstance(padding, str):
        return padding.upper()
    # explicit ((top, bottom), (left, right))
    return tuple((int(a), int(b)) for a, b in padding)


# --------------------------------------------------------------------------
# conv / dense / batch norm
# --------------------------------------------------------------------------


def _conv_init(rng, attrs, in_shapes, param_dtype):
    kh, kw = _pair(attrs.get("kernel_size", 3))
    cin = in_shapes[0][-1]
    groups = int(attrs.get("groups", 1))
    cout = int(attrs["features"])
    fan_in = kh * kw * (cin // groups)
    k_key, _ = jax.random.split(rng)
    kernel = jax.random.normal(
        k_key, (kh, kw, cin // groups, cout), param_dtype
    ) * jnp.sqrt(2.0 / fan_in).astype(param_dtype)
    params = {"kernel": kernel}
    if attrs.get("use_bias", False):
        params["bias"] = jnp.zeros((cout,), param_dtype)
    return params


@register_op("conv", init=_conv_init)
def conv_apply(params, inputs, attrs):
    (x,) = inputs
    strides = _pair(attrs.get("strides", 1))
    dilation = _pair(attrs.get("dilation", 1))
    kernel = params["kernel"].astype(x.dtype)
    kh, kw = kernel.shape[0], kernel.shape[1]
    out = lax.conv_general_dilated(
        x,
        kernel,
        window_strides=strides,
        padding=_conv_padding(attrs.get("padding", "SAME"), (kh, kw), dilation),
        rhs_dilation=dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=int(attrs.get("groups", 1)),
    )
    if "bias" in params:
        out = out + params["bias"].astype(x.dtype)
    return out


def _depthwise_init(rng, attrs, in_shapes, param_dtype):
    kh, kw = _pair(attrs.get("kernel_size", 3))
    cin = in_shapes[0][-1]
    mult = int(attrs.get("depth_multiplier", 1))
    fan_in = kh * kw
    kernel = jax.random.normal(
        rng, (kh, kw, 1, cin * mult), param_dtype
    ) * jnp.sqrt(2.0 / fan_in).astype(param_dtype)
    params = {"kernel": kernel}
    if attrs.get("use_bias", False):
        params["bias"] = jnp.zeros((cin * mult,), param_dtype)
    return params


@register_op("depthwise_conv", init=_depthwise_init)
def depthwise_conv_apply(params, inputs, attrs):
    (x,) = inputs
    strides = _pair(attrs.get("strides", 1))
    dilation = _pair(attrs.get("dilation", 1))
    kernel = params["kernel"].astype(x.dtype)
    cin = x.shape[-1]
    out = lax.conv_general_dilated(
        x,
        kernel,
        window_strides=strides,
        padding=_conv_padding(
            attrs.get("padding", "SAME"), kernel.shape[:2], dilation
        ),
        rhs_dilation=dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=cin,
    )
    if "bias" in params:
        out = out + params["bias"].astype(x.dtype)
    return out


def _dense_init(rng, attrs, in_shapes, param_dtype):
    cin = in_shapes[0][-1]
    cout = int(attrs["features"])
    kernel = jax.random.normal(rng, (cin, cout), param_dtype) * jnp.sqrt(
        1.0 / cin
    ).astype(param_dtype)
    params = {"kernel": kernel}
    if attrs.get("use_bias", True):
        params["bias"] = jnp.zeros((cout,), param_dtype)
    return params


@register_op("dense", init=_dense_init)
def dense_apply(params, inputs, attrs):
    (x,) = inputs
    out = x @ params["kernel"].astype(x.dtype)
    if "bias" in params:
        out = out + params["bias"].astype(x.dtype)
    return out


def _bn_init(rng, attrs, in_shapes, param_dtype):
    del rng
    c = in_shapes[0][-1]
    return {
        "scale": jnp.ones((c,), param_dtype),
        "bias": jnp.zeros((c,), param_dtype),
        "mean": jnp.zeros((c,), param_dtype),
        "var": jnp.ones((c,), param_dtype),
    }


@register_op("batch_norm", init=_bn_init)
def batch_norm_apply(params, inputs, attrs):
    """Inference-mode BN: normalize with stored statistics."""
    (x,) = inputs
    eps = float(attrs.get("eps", 1e-3))
    # Fold to a single multiply-add so XLA fuses it into the conv.
    inv = lax.rsqrt(params["var"].astype(jnp.float32) + eps)
    scale = (params["scale"].astype(jnp.float32) * inv).astype(x.dtype)
    shift = (
        params["bias"].astype(jnp.float32)
        - params["mean"].astype(jnp.float32) * params["scale"].astype(jnp.float32) * inv
    ).astype(x.dtype)
    return x * scale + shift


def _separable_init(rng, attrs, in_shapes, param_dtype):
    kh, kw = _pair(attrs.get("kernel_size", 3))
    cin = in_shapes[0][-1]
    mult = int(attrs.get("depth_multiplier", 1))
    cout = int(attrs["features"])
    k1, k2 = jax.random.split(rng)
    params = {
        "dw_kernel": jax.random.normal(
            k1, (kh, kw, 1, cin * mult), param_dtype
        ) * jnp.sqrt(2.0 / (kh * kw)).astype(param_dtype),
        "pw_kernel": jax.random.normal(
            k2, (1, 1, cin * mult, cout), param_dtype
        ) * jnp.sqrt(2.0 / (cin * mult)).astype(param_dtype),
    }
    if attrs.get("use_bias", True):
        params["bias"] = jnp.zeros((cout,), param_dtype)
    return params


@register_op("separable_conv", init=_separable_init)
def separable_conv_apply(params, inputs, attrs):
    """Depthwise kxk then pointwise 1x1 as one op (Keras
    SeparableConv2D), so checkpoints keyed by the Keras layer name map
    onto a single node."""
    (x,) = inputs
    strides = _pair(attrs.get("strides", 1))
    dilation = _pair(attrs.get("dilation", 1))
    dw = params["dw_kernel"].astype(x.dtype)
    out = lax.conv_general_dilated(
        x,
        dw,
        window_strides=strides,
        padding=_conv_padding(attrs.get("padding", "SAME"), dw.shape[:2], dilation),
        rhs_dilation=dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=x.shape[-1],
    )
    out = lax.conv_general_dilated(
        out,
        params["pw_kernel"].astype(x.dtype),
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "bias" in params:
        out = out + params["bias"].astype(x.dtype)
    return out


# --------------------------------------------------------------------------
# pooling / padding / reshaping
# --------------------------------------------------------------------------


def _pool_dims(attrs):
    wh, ww = _pair(attrs.get("window", 2))
    sh, sw = _pair(attrs.get("strides", attrs.get("window", 2)))
    padding = attrs.get("padding", "VALID")
    if isinstance(padding, str):
        padding = padding.upper()
    else:
        padding = ((0, 0), *[(int(a), int(b)) for a, b in padding], (0, 0))
    return (wh, ww), (sh, sw), padding


@register_op("max_pool")
def max_pool_apply(params, inputs, attrs):
    (x,) = inputs
    (wh, ww), (sh, sw), padding = _pool_dims(attrs)
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        (1, wh, ww, 1),
        (1, sh, sw, 1),
        padding,
    )


@register_op("avg_pool")
def avg_pool_apply(params, inputs, attrs):
    """Average pool that excludes padding from the count (TF semantics,
    which the reference's Keras models rely on for SAME-padded pools)."""
    (x,) = inputs
    (wh, ww), (sh, sw), padding = _pool_dims(attrs)
    dims, strides = (1, wh, ww, 1), (1, sh, sw, 1)
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
    if padding == "VALID":
        return summed / (wh * ww)
    ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
    counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, padding)
    return summed / counts


@register_op("global_avg_pool")
def global_avg_pool_apply(params, inputs, attrs):
    (x,) = inputs
    out = jnp.mean(x, axis=(1, 2), keepdims=bool(attrs.get("keepdims", False)))
    return out


@register_op("global_max_pool")
def global_max_pool_apply(params, inputs, attrs):
    (x,) = inputs
    return jnp.max(x, axis=(1, 2), keepdims=bool(attrs.get("keepdims", False)))


@register_op("zero_pad")
def zero_pad_apply(params, inputs, attrs):
    (x,) = inputs
    (pt, pb), (pl, pr) = [tuple(p) for p in attrs["padding"]]
    return jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))


@register_op("crop")
def crop_apply(params, inputs, attrs):
    (x,) = inputs
    (ct, cb), (cl, cr) = [tuple(p) for p in attrs["cropping"]]
    h, w = x.shape[1], x.shape[2]
    return x[:, ct : h - cb, cl : w - cr, :]


@register_op("flatten")
def flatten_apply(params, inputs, attrs):
    (x,) = inputs
    return x.reshape(x.shape[0], -1)


@register_op("reshape")
def reshape_apply(params, inputs, attrs):
    (x,) = inputs
    return x.reshape((x.shape[0], *attrs["shape"]))


@register_op("identity")
def identity_apply(params, inputs, attrs):
    (x,) = inputs
    return x


# Dropout at inference time is the identity (the reference only ever runs
# inference: reference src/node.py:129 calls model.predict).
@register_op("dropout")
def dropout_apply(params, inputs, attrs):
    (x,) = inputs
    return x


# --------------------------------------------------------------------------
# merges
# --------------------------------------------------------------------------


@register_op("add")
def add_apply(params, inputs, attrs):
    out = inputs[0]
    for x in inputs[1:]:
        out = out + x
    return out


@register_op("multiply")
def multiply_apply(params, inputs, attrs):
    out = inputs[0]
    for x in inputs[1:]:
        out = out * x
    return out


@register_op("concat")
def concat_apply(params, inputs, attrs):
    return jnp.concatenate(list(inputs), axis=int(attrs.get("axis", -1)))


# --------------------------------------------------------------------------
# activations
# --------------------------------------------------------------------------


@register_op("relu")
def relu_apply(params, inputs, attrs):
    return jax.nn.relu(inputs[0])


@register_op("relu6")
def relu6_apply(params, inputs, attrs):
    return jax.nn.relu6(inputs[0])


@register_op("sigmoid")
def sigmoid_apply(params, inputs, attrs):
    return jax.nn.sigmoid(inputs[0])


@register_op("tanh")
def tanh_apply(params, inputs, attrs):
    return jnp.tanh(inputs[0])


@register_op("swish")
def swish_apply(params, inputs, attrs):
    return jax.nn.silu(inputs[0])


@register_op("gelu")
def gelu_apply(params, inputs, attrs):
    return jax.nn.gelu(inputs[0], approximate=bool(attrs.get("approximate", True)))


@register_op("softmax")
def softmax_apply(params, inputs, attrs):
    return jax.nn.softmax(inputs[0], axis=int(attrs.get("axis", -1)))


@register_op("scale")
def scale_apply(params, inputs, attrs):
    """x * constant (InceptionResNetV2 residual scaling)."""
    return inputs[0] * float(attrs["value"])


@register_op("rescale")
def rescale_apply(params, inputs, attrs):
    """x * scale + offset (Keras Rescaling, e.g. EfficientNet's
    in-model 1/255)."""
    return inputs[0] * float(attrs.get("scale", 1.0)) + float(
        attrs.get("offset", 0.0)
    )


def _normalization_init(rng, attrs, in_shapes, param_dtype):
    del rng
    if attrs.get("mean") is not None:
        return {}  # statistics baked into attrs, nothing to learn/load
    c = in_shapes[0][-1]
    return {
        "mean": jnp.zeros((c,), param_dtype),
        "var": jnp.ones((c,), param_dtype),
    }


@register_op("normalization", init=_normalization_init)
def normalization_apply(params, inputs, attrs):
    """Keras Normalization (adapted feature scaling):
    (x - mean) / max(sqrt(var), eps), eps = Keras backend epsilon."""
    (x,) = inputs
    if "mean" in params:
        mean = params["mean"].astype(jnp.float32)
        var = params["var"].astype(jnp.float32)
    else:
        mean = jnp.asarray(attrs["mean"], jnp.float32)
        var = jnp.asarray(attrs["variance"], jnp.float32)
    denom = jnp.maximum(jnp.sqrt(var), 1e-7)
    return ((x.astype(jnp.float32) - mean) / denom).astype(x.dtype)
