"""Pallas flash attention for TPU.

The hot op of the transformer stack (SURVEY.md §5 notes the reference has
no attention at all; BERT-base in BASELINE.json is served through the
pipeline, and long-context support is first-class here). This kernel
keeps the S×S score matrix out of HBM entirely: each (batch·head,
q-block) grid cell streams K/V blocks through VMEM with the online
softmax recurrence, so memory is O(S·D) instead of O(S²) and the two
matmuls per block land on the MXU back-to-back.

`multi_head_attention` (defer_tpu/ops/attention.py) dispatches here on
TPU and falls back to the XLA einsum path elsewhere; tests run this
kernel in interpreter mode on CPU against that reference.

Differentiable: a custom VJP recomputes attention with the XLA
reference implementation in the backward pass (flash-style
rematerialization — nothing but q/k/v is saved for backward).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

# Finite stand-in for -inf: keeps fully-masked rows NaN-free in the
# online-softmax recurrence (exp(MASK - MASK) would be NaN with -inf).
_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


def _pick_block(s: int, preferred: int) -> int:
    """Largest divisor of `s` that is <= preferred (>= 8 when possible)."""
    b = min(preferred, s)
    while b > 1 and s % b:
        b -= 1
    return b


def _mha_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    *,
    sm_scale: float,
    causal: bool,
    block_k: int,
):
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (bq, d)
    bq, d = q.shape
    s_k = k_ref.shape[1]
    q_start = pl.program_id(1) * bq

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, block_k)
        if causal:
            rows = q_start + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0
            )
            cols = i * block_k + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1
            )
            s = jnp.where(rows >= cols, s, _MASK_VALUE)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + lax.dot_general(
            p,
            v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    num_k = s_k // block_k
    if causal:
        # Only blocks intersecting the causal triangle of this q block.
        num_k = jnp.minimum(
            num_k, (q_start + bq + block_k - 1) // block_k
        )
    init = (
        jnp.full((bq,), _MASK_VALUE, jnp.float32),
        jnp.zeros((bq,), jnp.float32),
        jnp.zeros((bq, d), jnp.float32),
    )
    _, l, acc = lax.fori_loop(0, num_k, body, init)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd_impl(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    interpret: bool,
    block_q: int = 256,
    block_k: int = 256,
) -> jax.Array:
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    if s_q < 8 or s_k < 8:
        raise ValueError(f"sequence too short for the TPU kernel: {s_q}x{s_k}")
    if causal and s_q != s_k:
        raise ValueError("causal flash kernel requires s_q == s_k")
    bq = _pick_block(s_q, block_q)
    bk = _pick_block(s_k, block_k)
    if bq < 8 or bk < 8:
        raise ValueError(
            f"no tile-friendly block split for seq lens {s_q}/{s_k}"
        )
    qf = q.reshape(b * h, s_q, d)
    kf = k.reshape(b * h, s_k, d)
    vf = v.reshape(b * h, s_k, d)
    kernel = functools.partial(
        _mha_kernel,
        sm_scale=d**-0.5,
        causal=causal,
        block_k=bk,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s_q, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _flash(causal: bool, interpret: bool, q, k, v):
    return _flash_fwd_impl(q, k, v, causal=causal, interpret=interpret)


def _flash_fwd(causal, interpret, q, k, v):
    return _flash(causal, interpret, q, k, v), (q, k, v)


def _flash_bwd(causal, interpret, res, g):
    # Flash-style rematerialization: recompute attention with the XLA
    # reference implementation and differentiate that. Saves only q/k/v
    # for backward; XLA fuses the recompute into the backward matmuls.
    from defer_tpu.ops.attention import attention_reference

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(q_, k_, v_, causal=causal),
        q,
        k,
        v,
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _decode_lo_hi(p_b, block_k: int, window: int | None):
    """First/last LIVE K-block (inclusive) for a sequence whose last
    valid key is `p_b`: blocks wholly outside [pos-window+1, pos] are
    dead. Shared by the kernel's compute gate and the index maps'
    DMA-clamping so the two can never disagree."""
    hi = p_b // block_k
    lo = (
        jnp.maximum(p_b - window + 1, 0) // block_k
        if window is not None
        else jnp.int32(0)
    )
    return lo, hi


def _decode_kernel(
    pos_ref,
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    sm_scale: float,
    block_k: int,
    window: int | None,
    num_kb: int,
):
    """One (batch, kv-head, k-block) cell: the query GROUP (G rows
    sharing this KV head — GQA) folds one block_k-row K/V tile into the
    online-softmax carry held in VMEM scratch (the k-block axis is the
    innermost grid dim, so scratch persists across it per (batch,
    head)). VMEM residency is O(block_k), not O(max_len): the index
    maps stage only this cell's tile. Dead blocks — wholly outside
    [pos-window+1, pos] — are compute-gated off here AND clamped to a
    live block index in the index maps, so revisiting the same tile
    issues no new DMA; decode stays O(live rows) in both bandwidth and
    compute."""
    kb = pl.program_id(2)
    p_b = pos_ref[pl.program_id(0)]
    lo, hi = _decode_lo_hi(p_b, block_k, window)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _MASK_VALUE, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when((kb >= lo) & (kb <= hi))
    def _fold():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # (G, d)
        g = q.shape[0]
        k = k_ref[0, 0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0, 0].astype(jnp.float32)
        s = lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (G, block_k)
        cols = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (g, block_k), 1
        )
        mask = cols <= p_b
        if window is not None:
            mask &= cols > p_b - window
        s = jnp.where(mask, s, _MASK_VALUE)
        m = m_scr[:]
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + p.sum(axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + lax.dot_general(
            p,
            v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kb == num_kb - 1)
    def _emit():
        o_ref[0, 0] = (acc_scr[:] / l_scr[:][:, None]).astype(
            o_ref.dtype
        )


def flash_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    pos: jax.Array,
    *,
    window: int | None = None,
    interpret: bool = False,
    block_k: int = 256,
) -> jax.Array:
    """Flash-decode: ONE query token per sequence against the KV cache
    — the serving hot op (decode is cache-bandwidth bound; this fuses
    mask + online softmax + weighted sum into one pass over the live
    cache rows and never materializes the [B, H, S] score matrix in
    HBM).

    q [B, Hq, Dh]; k/v [B, Hkv, S, Dh] (GQA: Hq = G*Hkv, the group
    attends its shared KV head); pos [B] int32 = index of each
    sequence's last valid key, INCLUSIVE (per-slot depths — continuous
    batching — are the native shape; broadcast a scalar for uniform
    batches). Returns [B, Hq, Dh].

    Query groups narrower than 8 rows are zero-padded to the TPU
    sublane tile and sliced back (padded rows attend garbage that is
    discarded). Positions ride scalar prefetch (SMEM): the K-block
    index maps read them to clamp dead blocks onto a live tile, so
    only O(block_k) K/V rows are ever VMEM-resident and dead grid
    cells issue no DMA.
    """
    b, hq, d = q.shape
    _, hkv, s, _ = k.shape
    if hq % hkv:
        raise ValueError(f"Hq={hq} must be a multiple of Hkv={hkv}")
    g = hq // hkv
    bk = _pick_block(s, block_k)
    if bk < 8:
        raise ValueError(f"no tile-friendly K block for cache len {s}")
    num_kb = s // bk
    g_pad = max(g, 8)
    qg = q.reshape(b, hkv, g, d)
    if g_pad != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, g_pad - g), (0, 0)))
    pos1 = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    kernel = functools.partial(
        _decode_kernel,
        sm_scale=d**-0.5,
        block_k=bk,
        window=window,
        num_kb=num_kb,
    )

    def kv_index(i, j, kb, pos_ref):
        lo, hi = _decode_lo_hi(pos_ref[i], bk, window)
        return (i, j, jnp.clip(kb, lo, hi), 0)

    from jax.experimental.pallas import tpu as pltpu

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hkv, num_kb),
        in_specs=[
            pl.BlockSpec(
                (1, 1, g_pad, d), lambda i, j, kb, pos_ref: (i, j, 0, 0)
            ),
            pl.BlockSpec((1, 1, bk, d), kv_index),
            pl.BlockSpec((1, 1, bk, d), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g_pad, d), lambda i, j, kb, pos_ref: (i, j, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((g_pad,), jnp.float32),
            pltpu.VMEM((g_pad,), jnp.float32),
            pltpu.VMEM((g_pad, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g_pad, d), q.dtype),
        interpret=interpret,
    )(pos1, qg, k, v)
    return out[:, :, :g, :].reshape(b, hq, d)


def _paged_decode_kernel(
    tables_ref,
    pos_ref,
    q_ref,
    k_ref,
    v_ref,
    *rest,
    sm_scale: float,
    block_size: int,
    window: int | None,
    num_tb: int,
    quantized: bool,
):
    """One (batch, kv-head, table-column) cell of paged flash-decode:
    like `_decode_kernel`, but the K/V tile staged for column `tb` is
    whatever POOL block the slot's table names — the index maps do the
    block-table indirection, so the kernel never sees a contiguous
    cache and nothing is gathered in HBM. Dead columns (wholly outside
    [pos-window+1, pos]) are compute-gated off here AND clamped onto a
    live column's pool block in the index maps, so per slot only its
    LIVE blocks are ever fetched — the bandwidth contract the paged
    pool exists for. Unallocated table entries point at trash block 0
    (runtime/paged.py invariant); the clamp keeps them un-fetched and
    the position mask keeps block-`hi` rows past `pos` unattended.

    With `quantized`, k_ref/v_ref are int8 pool tiles and two extra
    (1, 1) scale refs follow (per-(block, head) symmetric scales,
    staged through the SAME table indirection): the fold widens
    int8 -> f32 and multiplies the scale in VMEM, so HBM sees one
    byte per element — bandwidth, not just residency, halves."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest
    tb = pl.program_id(2)
    p_b = pos_ref[pl.program_id(0)]
    lo, hi = _decode_lo_hi(p_b, block_size, window)

    @pl.when(tb == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _MASK_VALUE, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when((tb >= lo) & (tb <= hi))
    def _fold():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # (G, d)
        g = q.shape[0]
        k = k_ref[0, 0].astype(jnp.float32)  # (block_size, d)
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s = lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (G, block_size)
        cols = tb * block_size + lax.broadcasted_iota(
            jnp.int32, (g, block_size), 1
        )
        mask = cols <= p_b
        if window is not None:
            mask &= cols > p_b - window
        s = jnp.where(mask, s, _MASK_VALUE)
        m = m_scr[:]
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + p.sum(axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + lax.dot_general(
            p,
            v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(tb == num_tb - 1)
    def _emit():
        o_ref[0, 0] = (acc_scr[:] / l_scr[:][:, None]).astype(
            o_ref.dtype
        )


def paged_flash_decode(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    tables: jax.Array,
    pos: jax.Array,
    *,
    window: int | None = None,
    interpret: bool = False,
    scale_k: jax.Array | None = None,
    scale_v: jax.Array | None = None,
) -> jax.Array:
    """Paged flash-decode: one query token per slot attending its
    BLOCK TABLE directly — no contiguous [B, Hkv, MB*bs, Dh] gather
    ever exists in HBM (the gather is runtime/paged.py's gathered-path
    cost this kernel deletes).

    q [B, Hq, Dh]; pool_k/pool_v [NB, Hkv, bs, Dh] — ONE layer of the
    shared block pool; tables [B, MB] int32 pool indices (unallocated
    entries = trash block 0); pos [B] int32 = each slot's last valid
    key, INCLUSIVE. Returns [B, Hq, Dh].

    Tables and positions ride scalar prefetch (SMEM): the K/V index
    maps resolve column tb of slot i to pool block tables[i, tb],
    clamped into the slot's live range so dead columns re-stage an
    already-resident tile instead of DMAing trash — per-slot bandwidth
    is O(live blocks), the paged-attention point. Query groups
    narrower than 8 rows are zero-padded to the TPU sublane tile and
    sliced back.

    For the int8 pool (runtime/paged.py kv_dtype="int8") pass
    scale_k/scale_v [NB, Hkv] f32 — per-(block, head) symmetric
    scales. They are regular inputs (NOT scalar prefetch: an
    [NB, Hkv] f32 tensor does not fit SMEM) staged one (1, 1) cell at
    a time through the same block-table index maps as the K/V tiles,
    and the kernel dequantizes in VMEM — HBM reads stay int8."""
    b, hq, d = q.shape
    nb, hkv, bs, _ = pool_k.shape
    if (scale_k is None) != (scale_v is None):
        raise ValueError("pass both scale_k and scale_v, or neither")
    quantized = scale_k is not None
    if hq % hkv:
        raise ValueError(f"Hq={hq} must be a multiple of Hkv={hkv}")
    if tables.ndim != 2 or tables.shape[0] != b:
        raise ValueError(
            f"tables must be [B={b}, MB], got {tables.shape}"
        )
    g = hq // hkv
    mb = tables.shape[1]
    g_pad = max(g, 8)
    qg = q.reshape(b, hkv, g, d)
    if g_pad != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, g_pad - g), (0, 0)))
    tables = jnp.asarray(tables, jnp.int32)
    pos1 = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    kernel = functools.partial(
        _paged_decode_kernel,
        sm_scale=d**-0.5,
        block_size=bs,
        window=window,
        num_tb=mb,
        quantized=quantized,
    )

    def kv_index(i, j, tb, tables_ref, pos_ref):
        lo, hi = _decode_lo_hi(pos_ref[i], bs, window)
        return (tables_ref[i, jnp.clip(tb, lo, hi)], j, 0, 0)

    def scale_index(i, j, tb, tables_ref, pos_ref):
        lo, hi = _decode_lo_hi(pos_ref[i], bs, window)
        return (tables_ref[i, jnp.clip(tb, lo, hi)], j)

    from jax.experimental.pallas import tpu as pltpu

    in_specs = [
        pl.BlockSpec(
            (1, 1, g_pad, d),
            lambda i, j, tb, tables_ref, pos_ref: (i, j, 0, 0),
        ),
        pl.BlockSpec((1, 1, bs, d), kv_index),
        pl.BlockSpec((1, 1, bs, d), kv_index),
    ]
    operands = [qg, pool_k, pool_v]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1), scale_index),
            pl.BlockSpec((1, 1), scale_index),
        ]
        operands += [
            jnp.asarray(scale_k, jnp.float32),
            jnp.asarray(scale_v, jnp.float32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, g_pad, d),
            lambda i, j, tb, tables_ref, pos_ref: (i, j, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((g_pad,), jnp.float32),
            pltpu.VMEM((g_pad,), jnp.float32),
            pltpu.VMEM((g_pad, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g_pad, d), q.dtype),
        interpret=interpret,
    )(tables, pos1, *operands)
    return out[:, :, :g, :].reshape(b, hq, d)


def _prefill_lo_hi(p0, t_q: int, block_size: int, window: int | None):
    """First/last LIVE K-block (inclusive) for a prefill window of
    `t_q` query tokens at absolute positions p0..p0+t_q-1: the last
    query attends through block (p0+t_q-1)//bs, the first one back to
    max(p0-window+1, 0). Shared by the compute gate and the index
    maps' DMA-clamping, mirroring `_decode_lo_hi`."""
    hi = (p0 + t_q - 1) // block_size
    lo = (
        jnp.maximum(p0 - window + 1, 0) // block_size
        if window is not None
        else jnp.int32(0)
    )
    return lo, hi


def _paged_prefill_kernel(
    tables_ref,
    start_ref,
    q_ref,
    k_ref,
    v_ref,
    *rest,
    sm_scale: float,
    block_size: int,
    group: int,
    window: int | None,
    num_tb: int,
    t_q: int,
    quantized: bool,
):
    """One (batch, kv-head, table-column) cell of paged flash-PREFILL:
    `_paged_decode_kernel` generalized from one query token to a
    window of T. The query tile is token-major — row r is query token
    r//G of group row r%G — so the causal mask is per ROW: row r
    attends keys at columns <= start + r//G (each window token sees
    the pool history plus its own predecessors in the window). K/V
    tiles still arrive through the block-table index maps: chunked
    prefill and the speculative verify forward read the pool directly,
    no contiguous gather. Rows padded past T*G attend a superset of
    live columns and are sliced off by the wrapper. With `quantized`,
    two (1, 1) per-(block, head) scale refs follow k/v and the fold
    dequantizes int8 tiles in VMEM (see `_paged_decode_kernel`)."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest
    tb = pl.program_id(2)
    p0 = start_ref[pl.program_id(0)]
    lo, hi = _prefill_lo_hi(p0, t_q, block_size, window)

    @pl.when(tb == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _MASK_VALUE, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    @pl.when((tb >= lo) & (tb <= hi))
    def _fold():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # (R, d)
        r = q.shape[0]
        k = k_ref[0, 0].astype(jnp.float32)  # (block_size, d)
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s = lax.dot_general(
            q,
            k,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (R, block_size)
        cols = tb * block_size + lax.broadcasted_iota(
            jnp.int32, (r, block_size), 1
        )
        qpos = (
            p0
            + lax.broadcasted_iota(jnp.int32, (r, block_size), 0) // group
        )
        mask = cols <= qpos
        if window is not None:
            mask &= cols > qpos - window
        s = jnp.where(mask, s, _MASK_VALUE)
        m = m_scr[:]
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + p.sum(axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + lax.dot_general(
            p,
            v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(tb == num_tb - 1)
    def _emit():
        o_ref[0, 0] = (acc_scr[:] / l_scr[:][:, None]).astype(
            o_ref.dtype
        )


def paged_flash_prefill(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    tables: jax.Array,
    start: jax.Array,
    *,
    window: int | None = None,
    interpret: bool = False,
    scale_k: jax.Array | None = None,
    scale_v: jax.Array | None = None,
) -> jax.Array:
    """Paged flash-prefill: a window of T query tokens per slot
    attending its block table directly — the prefill/verify companion
    to `paged_flash_decode`, closing the last full-pool gather in the
    serving path (chunked prefill and the speculative verify forward
    both route here).

    q [B, Hq, T, Dh] — T new tokens per slot, already rotated/projected
    for absolute positions start..start+T-1; pool_k/pool_v
    [NB, Hkv, bs, Dh] — ONE layer of the shared block pool, with the
    window's own K/V rows ALREADY scattered in (write-then-attend, the
    blockwise path's contract); tables [B, MB] int32 pool indices
    (unallocated entries = trash block 0); start [B] int32 = absolute
    position of each slot's FIRST window token. Returns [B, Hq, T, Dh].

    Causality is per window row: token t attends pool columns
    <= start+t, so rejected speculative rows left stale past `pos`
    are never read. The T*G query rows are zero-padded to the TPU
    sublane tile and sliced back; tables/start ride scalar prefetch so
    dead columns clamp onto live tiles exactly like the decode
    kernel. scale_k/scale_v [NB, Hkv] f32 enable the int8-pool path —
    same contract as `paged_flash_decode`."""
    b, hq, t_q, d = q.shape
    nb, hkv, bs, _ = pool_k.shape
    if (scale_k is None) != (scale_v is None):
        raise ValueError("pass both scale_k and scale_v, or neither")
    quantized = scale_k is not None
    if hq % hkv:
        raise ValueError(f"Hq={hq} must be a multiple of Hkv={hkv}")
    if tables.ndim != 2 or tables.shape[0] != b:
        raise ValueError(
            f"tables must be [B={b}, MB], got {tables.shape}"
        )
    g = hq // hkv
    mb = tables.shape[1]
    r = t_q * g
    r_pad = max(8, -(-r // 8) * 8)
    # Token-major query rows: row t*G + gi is window token t, group
    # row gi — the kernel recovers the token index as r//G.
    qg = (
        q.reshape(b, hkv, g, t_q, d)
        .transpose(0, 1, 3, 2, 4)
        .reshape(b, hkv, r, d)
    )
    if r_pad != r:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, r_pad - r), (0, 0)))
    tables = jnp.asarray(tables, jnp.int32)
    start1 = jnp.broadcast_to(
        jnp.asarray(start, jnp.int32).reshape(-1), (b,)
    )
    kernel = functools.partial(
        _paged_prefill_kernel,
        sm_scale=d**-0.5,
        block_size=bs,
        group=g,
        window=window,
        num_tb=mb,
        t_q=t_q,
        quantized=quantized,
    )

    def kv_index(i, j, tb, tables_ref, start_ref):
        lo, hi = _prefill_lo_hi(start_ref[i], t_q, bs, window)
        return (tables_ref[i, jnp.clip(tb, lo, hi)], j, 0, 0)

    def scale_index(i, j, tb, tables_ref, start_ref):
        lo, hi = _prefill_lo_hi(start_ref[i], t_q, bs, window)
        return (tables_ref[i, jnp.clip(tb, lo, hi)], j)

    from jax.experimental.pallas import tpu as pltpu

    in_specs = [
        pl.BlockSpec(
            (1, 1, r_pad, d),
            lambda i, j, tb, tables_ref, start_ref: (i, j, 0, 0),
        ),
        pl.BlockSpec((1, 1, bs, d), kv_index),
        pl.BlockSpec((1, 1, bs, d), kv_index),
    ]
    operands = [qg, pool_k, pool_v]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1), scale_index),
            pl.BlockSpec((1, 1), scale_index),
        ]
        operands += [
            jnp.asarray(scale_k, jnp.float32),
            jnp.asarray(scale_v, jnp.float32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, r_pad, d),
            lambda i, j, tb, tables_ref, start_ref: (i, j, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((r_pad,), jnp.float32),
            pltpu.VMEM((r_pad,), jnp.float32),
            pltpu.VMEM((r_pad, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, r_pad, d), q.dtype),
        interpret=interpret,
    )(tables, start1, *operands)
    out = out[:, :, :r, :].reshape(b, hkv, t_q, g, d)
    return out.transpose(0, 1, 3, 2, 4).reshape(b, hq, t_q, d)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention on (B, H, S, Dh) tensors; returns (B, H, S, Dh).

    Raises ValueError for shapes without a tile-friendly block split —
    `multi_head_attention` catches that in "auto" mode and falls back to
    the XLA path.
    """
    if q.ndim != 4:
        raise ValueError(f"expected (B, H, S, Dh), got {q.shape}")
    return _flash(causal, interpret, q, k, v)
