"""Op registry for the graph IR.

Every `OpNode.op` string resolves here. The reference delegated all
compute to opaque Keras layer objects (reference src/dag_util.py:25-26,
src/node.py:129); here each op is an explicit (init, apply) pair over
plain JAX arrays, so stages jit-compile into single XLA programs that
fuse onto the TPU's MXU/VPU.
"""

from defer_tpu.ops.registry import Op, get_op, op_names, register_op
from defer_tpu.ops import library as _library  # registers the standard ops
from defer_tpu.ops import transformer as _transformer  # transformer ops

__all__ = ["Op", "get_op", "op_names", "register_op"]
