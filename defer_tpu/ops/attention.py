"""Attention: XLA reference implementation + Pallas flash-attention hook.

`multi_head_attention` is the single entry point; the `mha` op and the
SPMD transformer pipeline both route through it. On TPU it can dispatch
to the Pallas flash kernel (defer_tpu/ops/pallas_attention.py); the XLA
einsum path is the fallback and the numerical reference in tests.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def _split_heads(x: jax.Array, num_heads: int) -> jax.Array:
    b, s, d = x.shape
    return x.reshape(b, s, num_heads, d // num_heads).transpose(0, 2, 1, 3)


def _merge_heads(x: jax.Array) -> jax.Array:
    b, h, s, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, h * dh)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    bias: jax.Array | None = None,
    causal: bool = False,
    window: int | None = None,
) -> jax.Array:
    """Plain softmax attention on (B, H, S, Dh) tensors, fp32 softmax.

    window=W adds Mistral-style sliding-window masking: query position
    p attends key positions (p-W, p] only (requires causal=True)."""
    if window is not None and not causal:
        raise NotImplementedError("window requires causal attention")
    dh = q.shape[-1]
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (dh**-0.5)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        s_q, s_k = logits.shape[-2:]
        qpos = jnp.arange(s_q)[:, None] + (s_k - s_q)
        kpos = jnp.arange(s_k)[None, :]
        mask = kpos <= qpos
        if window is not None:
            mask &= kpos > qpos - window
        logits = jnp.where(mask, logits, -jnp.inf)
    weights = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


def _pallas_available() -> bool:
    """True iff the default backend can actually run Mosaic kernels.

    `DEFER_TPU_PALLAS=1/0` forces the answer either way. Otherwise the
    backend must be a TPU *and* a directly-attached one: tunneled /
    experimental PJRT plugins (e.g. the "axon" remote-TPU transport)
    present themselves as platform "tpu" but cannot compile Mosaic —
    a pallas_call HANGS the transport rather than erroring (observed on
    TPU v5 lite behind axon), so probing at call time is not an option.
    Such plugins are registered in xla_bridge under their own factory
    name while the live client claims platform "tpu"; that mismatch is
    the detection.
    """
    import os

    forced = os.environ.get("DEFER_TPU_PALLAS")
    if forced is not None:
        return forced == "1"
    if jax.default_backend() != "tpu":
        return False
    try:
        from jax._src import xla_bridge as xb

        backend = jax.extend.backend.get_backend()
        for name, client in xb._backends.items():
            if client is backend and name != backend.platform:
                return False
    except Exception as e:  # noqa: BLE001 — fail CLOSED: a false yes hangs
        # If the probe breaks (jax internals moved), prefer the XLA
        # path: wrongly disabling pallas costs some speed; wrongly
        # enabling it on a tunneled backend hangs the transport.
        import warnings

        warnings.warn(
            f"pallas platform probe failed ({e!r}); using the XLA "
            "attention path — set DEFER_TPU_PALLAS=1 to force pallas",
            stacklevel=2,
        )
        return False
    return True


def multi_head_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    num_heads: int,
    bias: jax.Array | None = None,
    causal: bool = False,
    window: int | None = None,
    use_pallas: Any = "auto",
    sp_axis: str | None = None,
    sp_strategy: str = "ring",
) -> jax.Array:
    """Attention on (B, S, D) projections; returns (B, S, D).

    use_pallas: True / False / "auto" (pallas iff running on TPU and the
    shape is tile-friendly).

    window: sliding-window (Mistral-style) masking, causal only; the
    pallas path doesn't implement it, so it forces the XLA reference.

    sp_axis: mesh axis name for sequence parallelism — S is then the
    LOCAL sequence shard and attention runs ring / Ulysses over that
    axis (defer_tpu/parallel/sequence.py). Only valid inside shard_map.
    """
    qh, kh, vh = (_split_heads(t, num_heads) for t in (q, k, v))
    if sp_axis is not None:
        if bias is not None:
            raise NotImplementedError(
                "bias is not supported under sequence parallelism"
            )
        if window is not None:
            raise NotImplementedError(
                "sliding-window attention is not supported under "
                "sequence parallelism yet"
            )
        from defer_tpu.parallel.sequence import sequence_attention

        return _merge_heads(
            sequence_attention(
                qh, kh, vh,
                axis_name=sp_axis,
                strategy=sp_strategy,
                causal=causal,
            )
        )
    if use_pallas is True and window is not None:
        raise NotImplementedError(
            "the pallas flash kernel does not implement sliding-window "
            "masking; use use_pallas='auto' or False with window"
        )
    want_pallas = (
        use_pallas is True or (use_pallas == "auto" and _pallas_available())
    ) and window is None
    if want_pallas and bias is None:
        try:
            from defer_tpu.ops.pallas_attention import flash_attention
        except ImportError as e:
            if use_pallas is True:
                raise NotImplementedError(
                    "use_pallas=True requested but the Pallas flash-"
                    "attention kernel module is not available"
                ) from e
            flash_attention = None
        if flash_attention is not None:
            try:
                return _merge_heads(flash_attention(qh, kh, vh, causal=causal))
            except (NotImplementedError, ValueError):
                if use_pallas is True:
                    # An explicit request must not silently degrade.
                    raise
                # "auto": fall back to the XLA path.
    return _merge_heads(
        attention_reference(
            qh, kh, vh, bias=bias, causal=causal, window=window
        )
    )
