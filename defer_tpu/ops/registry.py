"""The op registry: name -> (init, apply)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Sequence

import jax

NodeParams = Mapping[str, jax.Array]
InitFn = Callable[
    [jax.Array, Mapping[str, Any], Sequence[tuple[int, ...]], Any], NodeParams
]
ApplyFn = Callable[[NodeParams, Sequence[jax.Array], Mapping[str, Any]], jax.Array]


@dataclasses.dataclass(frozen=True)
class Op:
    """An op kind.

    init(rng, attrs, in_shapes, param_dtype) -> params dict (maybe empty)
    apply(params, inputs, attrs) -> output array
    """

    name: str
    init: InitFn
    apply: ApplyFn


_REGISTRY: dict[str, Op] = {}


def register_op(
    name: str, *, init: InitFn | None = None
) -> Callable[[ApplyFn], ApplyFn]:
    """Decorator registering `apply` (and optional `init`) under `name`."""

    def deco(apply_fn: ApplyFn) -> ApplyFn:
        if name in _REGISTRY:
            raise ValueError(f"op {name!r} already registered")
        _REGISTRY[name] = Op(
            name=name, init=init if init is not None else _no_params, apply=apply_fn
        )
        return apply_fn

    return deco


def _no_params(rng, attrs, in_shapes, param_dtype) -> NodeParams:
    del rng, attrs, in_shapes, param_dtype
    return {}


def get_op(name: str) -> Op:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown op {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None


def op_names() -> list[str]:
    return sorted(_REGISTRY)
