"""Transformer ops (for the BERT-base config in BASELINE.json).

The reference never ran a transformer, but BERT-base encoder inference is
in its benchmark config list (BASELINE.json "configs"); pipeline stages
cut at encoder-block boundaries. Attention routes through
defer_tpu.ops.attention so the Pallas flash-attention kernel can be
swapped in on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from defer_tpu.ops.registry import register_op


def _embedding_init(rng, attrs, in_shapes, param_dtype):
    vocab = int(attrs["vocab_size"])
    dim = int(attrs["features"])
    table = jax.random.normal(rng, (vocab, dim), param_dtype) * 0.02
    return {"table": table}


@register_op("embedding", init=_embedding_init)
def embedding_apply(params, inputs, attrs):
    (ids,) = inputs
    return jnp.take(params["table"], ids, axis=0)


def _pos_embedding_init(rng, attrs, in_shapes, param_dtype):
    max_len = int(attrs["max_len"])
    dim = in_shapes[0][-1]
    table = jax.random.normal(rng, (max_len, dim), param_dtype) * 0.02
    return {"table": table}


@register_op("pos_embedding", init=_pos_embedding_init)
def pos_embedding_apply(params, inputs, attrs):
    """Adds a learned positional embedding to (B, S, D)."""
    (x,) = inputs
    seq = x.shape[1]
    return x + params["table"][:seq].astype(x.dtype)


def _layer_norm_init(rng, attrs, in_shapes, param_dtype):
    del rng
    dim = in_shapes[0][-1]
    return {
        "scale": jnp.ones((dim,), param_dtype),
        "bias": jnp.zeros((dim,), param_dtype),
    }


@register_op("layer_norm", init=_layer_norm_init)
def layer_norm_apply(params, inputs, attrs):
    (x,) = inputs
    eps = float(attrs.get("eps", 1e-12))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    normed = (xf - mean) * jax.lax.rsqrt(var + eps)
    out = normed * params["scale"].astype(jnp.float32) + params["bias"].astype(
        jnp.float32
    )
    return out.astype(x.dtype)


def _mha_init(rng, attrs, in_shapes, param_dtype):
    dim = in_shapes[0][-1]
    num_heads = int(attrs["num_heads"])
    if dim % num_heads:
        raise ValueError(f"model dim {dim} not divisible by {num_heads} heads")
    keys = jax.random.split(rng, 4)
    scale = dim**-0.5
    return {
        "wq": jax.random.normal(keys[0], (dim, dim), param_dtype) * scale,
        "wk": jax.random.normal(keys[1], (dim, dim), param_dtype) * scale,
        "wv": jax.random.normal(keys[2], (dim, dim), param_dtype) * scale,
        "wo": jax.random.normal(keys[3], (dim, dim), param_dtype) * scale,
        "bq": jnp.zeros((dim,), param_dtype),
        "bk": jnp.zeros((dim,), param_dtype),
        "bv": jnp.zeros((dim,), param_dtype),
        "bo": jnp.zeros((dim,), param_dtype),
    }


@register_op("mha", init=_mha_init)
def mha_apply(params, inputs, attrs):
    """Multi-head self-attention on (B, S, D).

    Optional second input: additive attention bias/mask broadcastable to
    (B, heads, S, S).
    """
    from defer_tpu.ops.attention import multi_head_attention

    x = inputs[0]
    mask = inputs[1] if len(inputs) > 1 else None
    num_heads = int(attrs["num_heads"])
    dt = x.dtype
    q = x @ params["wq"].astype(dt) + params["bq"].astype(dt)
    k = x @ params["wk"].astype(dt) + params["bk"].astype(dt)
    v = x @ params["wv"].astype(dt) + params["bv"].astype(dt)
    out = multi_head_attention(
        q,
        k,
        v,
        num_heads=num_heads,
        bias=mask,
        causal=bool(attrs.get("causal", False)),
        use_pallas=attrs.get("use_pallas", "auto"),
    )
    return out @ params["wo"].astype(dt) + params["bo"].astype(dt)


@register_op("take_token")
def take_token_apply(params, inputs, attrs):
    """Select one sequence position, e.g. the [CLS] token: (B,S,D)->(B,D)."""
    (x,) = inputs
    return x[:, int(attrs.get("index", 0)), :]


def _cls_token_init(rng, attrs, in_shapes, param_dtype):
    dim = in_shapes[0][-1]
    return {"token": jax.random.normal(rng, (1, 1, dim), param_dtype) * 0.02}


@register_op("cls_token", init=_cls_token_init)
def cls_token_apply(params, inputs, attrs):
    """Prepend a learned classification token: (B,S,D) -> (B,S+1,D)
    (ViT's [class] embedding; no reference analogue — the reference zoo
    is CNN-only)."""
    (x,) = inputs
    tok = jnp.broadcast_to(
        params["token"].astype(x.dtype), (x.shape[0], 1, x.shape[-1])
    )
    return jnp.concatenate([tok, x], axis=1)
