// Native host image preprocessing: fused bilinear resize + center-crop
// + per-channel affine (the ImageNet input transforms), uint8 NHWC in,
// float32 or bfloat16 NHWC out.
//
// The reference's host input path is PIL resize + numpy arithmetic on
// the driver (reference src/test.py:13-16); here the whole transform is
// one C++ pass so the feed thread keeps up with a TPU consuming >10k
// images/sec. Semantics match defer_tpu/runtime/data.py's numpy path
// exactly: short-side resize with half-pixel-centered bilinear
// sampling, center crop, then out = sample * scale + offset[channel],
// with an optional RGB->BGR swap (the caffe convention).
//
// C ABI only — consumed via ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -shared -fPIC imageproc.cpp -o libdeferimage.so -pthread

#include <algorithm>
#include <cmath>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Round-to-nearest-even truncation of an IEEE754 float to bfloat16
// (the top 16 bits), matching numpy/ml_dtypes casting.
inline uint16_t float_to_bf16(float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, sizeof(bits));
  const uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

struct PlanRow {
  int64_t lo;
  int64_t hi;
  float w;  // weight of hi sample
};

// Half-pixel-centered source coordinate plan for one output axis,
// matching _bilinear_resize_np: clip((i + 0.5) * src/dst - 0.5, 0,
// src-1), with the crop offset folded in.
std::vector<PlanRow> make_plan(int64_t src, int64_t dst, int64_t crop0,
                               int64_t out) {
  std::vector<PlanRow> plan(static_cast<size_t>(out));
  const double r = static_cast<double>(src) / static_cast<double>(dst);
  for (int64_t i = 0; i < out; ++i) {
    double pos = (static_cast<double>(i + crop0) + 0.5) * r - 0.5;
    pos = std::min(std::max(pos, 0.0), static_cast<double>(src - 1));
    const int64_t lo = static_cast<int64_t>(std::floor(pos));
    plan[static_cast<size_t>(i)] = {
        lo, std::min(lo + 1, src - 1),
        static_cast<float>(pos - static_cast<double>(lo))};
  }
  return plan;
}

struct Job {
  const uint8_t* src;
  int64_t h, w, c;
  const PlanRow* ys;
  const PlanRow* xs;
  int64_t size;
  const float* scale;   // per channel (post-swap order)
  const float* offset;  // per channel (post-swap order)
  int swap_rb;
  int out_bf16;
  void* dst;
};

void process_rows(const Job& job, int64_t row0, int64_t row1) {
  const int64_t c = job.c, w = job.w, size = job.size;
  float* out_f = static_cast<float*>(job.dst);
  uint16_t* out_h = static_cast<uint16_t*>(job.dst);
  for (int64_t i = row0; i < row1; ++i) {
    const PlanRow& py = job.ys[i];
    const uint8_t* top = job.src + py.lo * w * c;
    const uint8_t* bot = job.src + py.hi * w * c;
    const float wy = py.w;
    for (int64_t j = 0; j < size; ++j) {
      const PlanRow& px = job.xs[j];
      const uint8_t* tl = top + px.lo * c;
      const uint8_t* tr = top + px.hi * c;
      const uint8_t* bl = bot + px.lo * c;
      const uint8_t* br = bot + px.hi * c;
      const float wx = px.w;
      for (int64_t k = 0; k < c; ++k) {
        const float t = static_cast<float>(tl[k]) +
                        (static_cast<float>(tr[k]) - static_cast<float>(tl[k])) * wx;
        const float b = static_cast<float>(bl[k]) +
                        (static_cast<float>(br[k]) - static_cast<float>(bl[k])) * wx;
        const float v = t + (b - t) * wy;
        const int64_t ko = job.swap_rb && c == 3 ? c - 1 - k : k;
        const float r = v * job.scale[ko] + job.offset[ko];
        const int64_t idx = (i * size + j) * c + ko;
        if (job.out_bf16) {
          out_h[idx] = float_to_bf16(r);
        } else {
          out_f[idx] = r;
        }
      }
    }
  }
}

}  // namespace

extern "C" {

// Preprocess n HWC uint8 images (contiguous NHWC) into n size*size*c
// outputs. scale/offset are length-c, indexed by OUTPUT channel (after
// the optional R<->B swap). Returns 0 on success, nonzero on bad args.
int defer_preprocess(const uint8_t* src, int64_t n, int64_t h, int64_t w,
                     int64_t c, int64_t size, const float* scale,
                     const float* offset, int swap_rb, int out_bf16,
                     int64_t num_threads, void* dst) {
  if (!src || !dst || n < 0 || h <= 0 || w <= 0 || c <= 0 || size <= 0) {
    return 1;
  }
  if (n == 0) return 0;  // nothing to do (and no zero-size pool math)
  // Short-side resize dims, then centered crop offsets (matching
  // _resize_center_crop; std::nearbyint under the default FP
  // environment rounds half-to-even, like Python's round()).
  const double s =
      static_cast<double>(size) / static_cast<double>(std::min(h, w));
  const int64_t nh =
      std::max(size, static_cast<int64_t>(std::nearbyint(h * s)));
  const int64_t nw =
      std::max(size, static_cast<int64_t>(std::nearbyint(w * s)));
  const int64_t top = (nh - size) / 2;
  const int64_t left = (nw - size) / 2;
  const auto ys = make_plan(h, nh, top, size);
  const auto xs = make_plan(w, nw, left, size);

  const int64_t out_elem = out_bf16 ? 2 : 4;
  const int64_t total_rows = n * size;
  auto run_range = [&](int64_t g0, int64_t g1) {
    // Global row index g = img * size + row; regroup into contiguous
    // per-image spans so each Job is set up once per span.
    int64_t g = g0;
    while (g < g1) {
      const int64_t img = g / size;
      const int64_t row0 = g % size;
      const int64_t row1 = std::min<int64_t>(size, row0 + (g1 - g));
      Job job{src + img * h * w * c,
              h,
              w,
              c,
              ys.data(),
              xs.data(),
              size,
              scale,
              offset,
              swap_rb,
              out_bf16,
              static_cast<uint8_t*>(dst) + img * size * size * c * out_elem};
      process_rows(job, row0, row1);
      g += row1 - row0;
    }
  };
  // One pool over ALL n*size output rows (not per image): thread
  // create/join overhead is paid once per call, and a batch keeps
  // every worker busy across image boundaries.
  int64_t threads = std::max<int64_t>(1, num_threads);
  threads = std::min(threads, total_rows);
  if (threads == 1) {
    run_range(0, total_rows);
  } else {
    std::vector<std::thread> pool;
    pool.reserve(static_cast<size_t>(threads));
    const int64_t chunk = (total_rows + threads - 1) / threads;
    for (int64_t t = 0; t < threads; ++t) {
      const int64_t r0 = t * chunk;
      const int64_t r1 = std::min(r0 + chunk, total_rows);
      if (r0 >= r1) break;
      pool.emplace_back([&run_range, r0, r1] { run_range(r0, r1); });
    }
    for (auto& th : pool) th.join();
  }
  return 0;
}

}  // extern "C"
