// Native transfer codec: byteshuffle + zstd.
//
// The reference compresses every weight and activation hop with
// ZFP + LZ4 (reference src/dispatcher.py:89-92, src/node.py:93-96) —
// a float-aware transform feeding a general-purpose compressor. This
// is the TPU-native equivalent for the host/DCN seam (ICI needs no
// codec; SURVEY.md §2 native-component plan): the float-aware
// transform is a byte-plane shuffle (groups sign/exponent bytes of
// consecutive elements, which entropy-codes far better than
// interleaved IEEE754), and the compressor is zstd.
//
// C ABI only — consumed via ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -shared -fPIC codec.cpp -o libdefercodec.so -lzstd

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <vector>

#include <zstd.h>

namespace {

// Scatter element bytes into per-position planes: for elem size k and n
// elements, dst[j*n + i] = src[i*k + j]. Blocked over i for locality.
void byteshuffle(const uint8_t* src, uint8_t* dst, size_t n, size_t k) {
  constexpr size_t kBlock = 4096;
  for (size_t i0 = 0; i0 < n; i0 += kBlock) {
    const size_t i1 = i0 + kBlock < n ? i0 + kBlock : n;
    for (size_t j = 0; j < k; ++j) {
      uint8_t* d = dst + j * n;
      const uint8_t* s = src + j;
      for (size_t i = i0; i < i1; ++i) d[i] = s[i * k];
    }
  }
}

void byteunshuffle(const uint8_t* src, uint8_t* dst, size_t n, size_t k) {
  constexpr size_t kBlock = 4096;
  for (size_t i0 = 0; i0 < n; i0 += kBlock) {
    const size_t i1 = i0 + kBlock < n ? i0 + kBlock : n;
    for (size_t j = 0; j < k; ++j) {
      const uint8_t* s = src + j * n;
      uint8_t* d = dst + j;
      for (size_t i = i0; i < i1; ++i) d[i * k] = s[i];
    }
  }
}

}  // namespace

extern "C" {

// Upper bound on encode output for nbytes of input.
size_t defer_codec_bound(size_t nbytes) { return ZSTD_compressBound(nbytes); }

// Encode nbytes of src (elem_size-byte elements) into dst.
// Returns compressed size, or 0 on error (dst_cap too small / zstd
// failure). elem_size==1 skips the shuffle.
size_t defer_codec_encode(const uint8_t* src, size_t nbytes, size_t elem_size,
                          int level, uint8_t* dst, size_t dst_cap) {
  const uint8_t* input = src;
  std::vector<uint8_t> shuffled;
  if (elem_size > 1 && nbytes % elem_size == 0) {
    shuffled.resize(nbytes);
    byteshuffle(src, shuffled.data(), nbytes / elem_size, elem_size);
    input = shuffled.data();
  }
  const size_t r = ZSTD_compress(dst, dst_cap, input, nbytes, level);
  return ZSTD_isError(r) ? 0 : r;
}

// Decode into exactly nbytes_out at dst. Returns nbytes_out, or 0 on
// error (corrupt frame / size mismatch).
size_t defer_codec_decode(const uint8_t* src, size_t src_len, uint8_t* dst,
                          size_t nbytes_out, size_t elem_size) {
  if (elem_size > 1 && nbytes_out % elem_size == 0) {
    std::vector<uint8_t> shuffled(nbytes_out);
    const size_t r = ZSTD_decompress(shuffled.data(), nbytes_out, src, src_len);
    if (ZSTD_isError(r) || r != nbytes_out) return 0;
    byteunshuffle(shuffled.data(), dst, nbytes_out / elem_size, elem_size);
    return nbytes_out;
  }
  const size_t r = ZSTD_decompress(dst, nbytes_out, src, src_len);
  return (ZSTD_isError(r) || r != nbytes_out) ? 0 : r;
}

}  // extern "C"
