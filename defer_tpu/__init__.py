"""defer_tpu — a TPU-native pipeline-parallel DNN inference framework.

Built from scratch in JAX/XLA with the capabilities of DEFER
(arXiv 2201.06769; reference impl at /root/reference). The reference's
dispatcher/compute-node/TCP-socket architecture (reference
src/dispatcher.py, src/node.py, src/node_state.py) is replaced by a
single-controller JAX program: a model is partitioned at named cut-points
into jit-compiled stages, each pinned to one TPU core, and activations
flow core-to-core over ICI instead of ZFP+LZ4-compressed sockets.

Public API (mirrors the reference's user model, reference src/test.py:21,47):

    from defer_tpu import DEFER
    defer = DEFER()                       # discovers the TPU mesh
    defer.run_defer(model, ["add_8"], input_q, output_q)
"""

from defer_tpu.api import DEFER, run_local_inference
from defer_tpu.config import DeferConfig
from defer_tpu.graph.ir import Graph, GraphBuilder, OpNode
from defer_tpu.graph.partition import (
    PartitionError,
    partition,
    stage_params,
    validate_cut_points,
)
from defer_tpu.graph.serialize import graph_from_json, graph_to_json
from defer_tpu import obs
from defer_tpu.parallel import (
    Pipeline,
    ReplicatedPipeline,
    ShardedInference,
    make_mesh,
)

__version__ = "0.5.0"

__all__ = [
    "DEFER",
    "DeferConfig",
    "Graph",
    "GraphBuilder",
    "OpNode",
    "PartitionError",
    "Pipeline",
    "ReplicatedPipeline",
    "ShardedInference",
    "graph_from_json",
    "graph_to_json",
    "make_mesh",
    "obs",
    "partition",
    "run_local_inference",
    "stage_params",
    "validate_cut_points",
]
