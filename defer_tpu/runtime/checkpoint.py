"""Checkpoint save/resume over the native codec.

The reference has no checkpointing at all — weights flow dispatcher ->
node once at startup (reference src/dispatcher.py:60-63, src/node.py:
68-70) and are lost with the process. Here params (any nested
str-keyed dict of arrays: GraphParams, SpmdBert params, train states'
param trees) serialize to a single self-describing file, each array
compressed through the runtime codec (defer_tpu/runtime/codec.py) —
the same seam the reference runs its ZFP+LZ4 pipe through.

bfloat16 (the TPU compute dtype, which numpy lacks) ships as a uint16
byte view with its logical dtype recorded in the manifest.

File: magic line, 8-byte LE manifest length, JSON manifest
[{key, dtype, frame_len}...], then the codec frames back-to-back.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import SingleDeviceSharding

from defer_tpu.runtime import codec

_MAGIC = b"DEFERTPU-CKPT-v1\n"
_SEP = "/"


def _flatten(tree: Mapping[str, Any], prefix: str = "") -> list[tuple[str, Any]]:
    out: list[tuple[str, Any]] = []
    for k in sorted(tree):
        if _SEP in k:
            raise ValueError(f"checkpoint keys may not contain {_SEP!r}: {k!r}")
        v = tree[k]
        path = f"{prefix}{k}"
        if isinstance(v, Mapping):
            out.extend(_flatten(v, f"{path}{_SEP}"))
        else:
            out.append((path, v))
    return out


def _unflatten(items: list[tuple[str, Any]]) -> dict:
    root: dict = {}
    for path, v in items:
        parts = path.split(_SEP)
        cur = root
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    return root


def save_checkpoint(path: str, params: Mapping[str, Any], *, level: int = 3) -> None:
    """Atomically write `params` to `path` (write temp + rename)."""
    entries = []
    frames = []
    for key, value in _flatten(params):
        arr = np.asarray(value)
        logical = arr.dtype.name
        if logical == "bfloat16":
            arr = arr.view(np.uint16)
        frame = codec.encode(arr, level=level)
        entries.append({"key": key, "dtype": logical, "frame_len": len(frame)})
        frames.append(frame)
    manifest = json.dumps(entries).encode()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<q", len(manifest)))
        f.write(manifest)
        for frame in frames:
            f.write(frame)
    os.replace(tmp, path)


def load_checkpoint(path: str) -> dict:
    """Read a checkpoint back into a nested dict of jnp arrays."""
    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path!r} is not a defer_tpu checkpoint")
        (mlen,) = struct.unpack("<q", f.read(8))
        entries = json.loads(f.read(mlen).decode())
        items = []
        for e in entries:
            arr = codec.decode(f.read(e["frame_len"]))
            if e["dtype"] == "bfloat16":
                arr = arr.view(jnp.bfloat16.dtype)
            value = jnp.asarray(arr)
            items.append((e["key"], value))
    return _unflatten(items)


# -- arbitrary pytrees (train states: params + optimizer + step) -----------


def save_pytree(path: str, tree: Any, *, level: int = 3) -> None:
    """Checkpoint any pytree (e.g. a TrainState: params dict + optax
    opt_state NamedTuples + step counter) by flattening to leaves.

    The tree *structure* is not serialized — restore requires a
    template with the same structure (`load_pytree`), which every
    training setup can rebuild via its init function. This is the
    standard resume pattern and keeps the on-disk format plain arrays.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    save_checkpoint(
        path,
        {"__leaves__": {str(i): leaf for i, leaf in enumerate(leaves)}},
        level=level,
    )


def load_pytree(path: str, template: Any) -> Any:
    """Restore a pytree saved by save_pytree into `template`'s
    structure (values of `template` are ignored; shapes/dtypes of the
    stored leaves win). Raises if the leaf count doesn't match."""
    stored = load_checkpoint(path)["__leaves__"]
    treedef = jax.tree_util.tree_structure(template)
    if treedef.num_leaves != len(stored):
        raise ValueError(
            f"checkpoint has {len(stored)} leaves but the template "
            f"structure expects {treedef.num_leaves}"
        )
    leaves = [stored[str(i)] for i in range(len(stored))]
    return jax.tree_util.tree_unflatten(treedef, leaves)


# -- sharded (SPMD / multi-host) checkpoints --------------------------------


def _shard_index_spans(
    index: tuple, shape: tuple[int, ...]
) -> tuple[tuple[int, int], ...]:
    """Normalize a shard's index (tuple of slices) to (start, stop)
    spans — JSON-serializable and comparable across save/restore."""
    return tuple(
        (0, dim) if sl == slice(None) else tuple(sl.indices(dim)[:2])
        for sl, dim in zip(index, shape)
    )


def save_sharded(
    dirpath: str, tree: Any, *, level: int = 3, save_id: Any = None
) -> None:
    """Checkpoint a pytree of (possibly distributed) jax.Arrays without
    gathering: each process writes one file holding only the shards it
    owns (replica_id == 0, so replicated data is stored exactly once
    across the job). The analogue of the reference's one-way weight
    shipping (reference src/dispatcher.py:60-63) but durable and
    distributed. Assumes a filesystem all hosts can read at restore
    (the standard multi-host checkpoint arrangement).

    `save_id` (e.g. the training step — a value every process already
    agrees on) is stamped into each shard's manifest; restore_sharded
    rejects shard sets with mismatched ids, catching a save that died
    after only some processes replaced their files.
    """
    os.makedirs(dirpath, exist_ok=True)
    entries = []
    frames = []
    for key, value in _flatten_pytree_keys(tree):
        if not isinstance(value, jax.Array):
            value = jnp.asarray(value)
        gshape = tuple(int(d) for d in value.shape)
        for shard in value.addressable_shards:
            if shard.replica_id != 0:
                continue
            arr = np.asarray(shard.data)
            logical = arr.dtype.name
            if logical == "bfloat16":
                arr = arr.view(np.uint16)
            frame = codec.encode(np.ascontiguousarray(arr), level=level)
            entries.append(
                {
                    "key": key,
                    "dtype": logical,
                    "global_shape": gshape,
                    "spans": _shard_index_spans(shard.index, gshape),
                    "frame_len": len(frame),
                }
            )
            frames.append(frame)
    manifest = json.dumps(
        {
            "process": jax.process_index(),
            "save_id": save_id,
            "entries": entries,
        }
    ).encode()
    # The process count rides in the filename so a restore can detect
    # stale shard files from an earlier save with a different job size
    # (mixing those would silently blend checkpoints).
    path = os.path.join(
        dirpath,
        f"shards-{jax.process_index():05d}-of-{jax.process_count():05d}"
        ".defer",
    )
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<q", len(manifest)))
        f.write(manifest)
        for frame in frames:
            f.write(frame)
    os.replace(tmp, path)


def _flatten_pytree_keys(tree: Any) -> list[tuple[str, Any]]:
    """jax key-path flatten -> ('a/b/0', leaf) pairs (stable across
    processes for identical tree structures)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in flat:
        segs = [
            str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
            for p in path
        ]
        for s in segs:
            if _SEP in s:
                # Same guard as _flatten: a '/' inside a key would alias
                # {'a/b': x} with {'a': {'b': y}} in the manifest.
                raise ValueError(
                    f"checkpoint keys may not contain {_SEP!r}: {s!r}"
                )
        out.append((_SEP.join(segs) or "__root__", leaf))
    return out


def restore_sharded(dirpath: str, like: Any) -> Any:
    """Rebuild a distributed pytree from a save_sharded directory.

    `like` carries the target structure, global shapes/dtypes and
    shardings: a pytree of jax.Arrays (e.g. a freshly-initialized
    state) or jax.ShapeDtypeStruct leaves with `.sharding` set. Each
    process reads every shard file it can see and assembles only its
    addressable pieces.
    """
    names = sorted(
        n for n in os.listdir(dirpath)
        if n.startswith("shards-") and n.endswith(".defer")
    )
    if not names:
        raise FileNotFoundError(f"no shard files under {dirpath!r}")
    counts = {n.rsplit("-of-", 1)[-1] for n in names}
    if len(counts) != 1 or len(names) != int(counts.pop().split(".")[0]):
        raise ValueError(
            f"{dirpath!r} holds a mixed or incomplete shard set "
            f"({names}); remove stale files from a previous save"
        )

    # Decode only what this process will actually place: the needed
    # spans per key, from `like`'s shardings (a multi-host restore must
    # not decompress the whole checkpoint on every host).
    flat_like = _flatten_pytree_keys(like)
    needed: dict[str, set[tuple]] = {}
    for key, leaf in flat_like:
        gshape = tuple(int(d) for d in leaf.shape)
        sharding = getattr(leaf, "sharding", None)
        spans = {tuple((0, d) for d in gshape)}
        if sharding is not None:
            for index in sharding.addressable_devices_indices_map(
                gshape
            ).values():
                spans.add(_shard_index_spans(index, gshape))
        needed[key] = spans

    pieces: dict[str, dict[tuple, np.ndarray]] = {}
    save_ids: set[Any] = set()
    for name in names:
        with open(os.path.join(dirpath, name), "rb") as f:
            if f.read(len(_MAGIC)) != _MAGIC:
                raise ValueError(f"{name!r} is not a defer_tpu checkpoint")
            (mlen,) = struct.unpack("<q", f.read(8))
            header = json.loads(f.read(mlen).decode())
            save_ids.add(json.dumps(header.get("save_id")))
            if len(save_ids) > 1:
                raise ValueError(
                    f"{dirpath!r} mixes shards from different saves "
                    f"(save_ids {sorted(save_ids)}); a previous save "
                    "likely died after replacing only some files"
                )
            entries = header["entries"]
            for e in entries:
                span = tuple(tuple(s) for s in e["spans"])
                if span not in needed.get(e["key"], ()):
                    f.seek(e["frame_len"], os.SEEK_CUR)
                    continue
                arr = codec.decode(f.read(e["frame_len"]))
                if e["dtype"] == "bfloat16":
                    arr = arr.view(jnp.bfloat16.dtype)
                pieces.setdefault(e["key"], {})[span] = arr
    leaves = []
    for key, leaf in flat_like:
        sharding = getattr(leaf, "sharding", None)
        gshape = tuple(int(d) for d in leaf.shape)
        by_span = pieces.get(key)
        if by_span is None:
            raise KeyError(f"checkpoint has no shards for leaf {key!r}")
        on_default_device = isinstance(
            sharding, SingleDeviceSharding
        ) and sharding.device_set == {jax.local_devices()[0]}
        if sharding is None or on_default_device:
            # Unsharded / default-single-device leaf: one full-array
            # piece, restored UNCOMMITTED (a device_put-committed
            # scalar would make the next jit reject it alongside
            # multi-device params — fresh-init states carry
            # uncommitted scalars). Non-default single-device leaves
            # (per-stage pinned buffers) keep their device via the
            # sharded branch below.
            full = by_span.get(tuple((0, d) for d in gshape))
            if full is None:
                raise ValueError(
                    f"leaf {key!r} has no full-array shard and no "
                    "target sharding to assemble against"
                )
            leaves.append(jnp.asarray(full).reshape(gshape))
            continue
        device_arrays = []
        for dev, index in sharding.addressable_devices_indices_map(
            gshape
        ).items():
            span = _shard_index_spans(index, gshape)
            piece = by_span.get(span)
            if piece is None:
                raise ValueError(
                    f"leaf {key!r}: no stored shard covers span {span} "
                    f"(stored: {sorted(by_span)[:4]}...)"
                )
            # The codec round-trips data, not rank (0-d arrays come
            # back 1-element); restore the span's exact local shape.
            local_shape = tuple(stop - start for start, stop in span)
            device_arrays.append(
                jax.device_put(np.asarray(piece).reshape(local_shape), dev)
            )
        leaves.append(
            jax.make_array_from_single_device_arrays(
                gshape, sharding, device_arrays
            )
        )
    treedef = jax.tree_util.tree_structure(like)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# -- orbax interop ---------------------------------------------------------


def save_orbax(path: str, tree: Any) -> None:
    """Write a pytree as an orbax StandardCheckpoint — ecosystem
    interop so training stacks already standardized on orbax (flax,
    maxtext-style setups) can consume this framework's states without
    the native format. The native format (save_pytree) stays the
    default: single file, codec-compressed, no directory protocol."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        # force=True allows repeated saves to one path. NOTE: unlike
        # the native save_pytree (tmp file + os.replace), orbax
        # removes the old checkpoint BEFORE committing the new one —
        # a crash mid-save can lose both. For crash-safe rotation,
        # save to a fresh path per step (orbax's CheckpointManager
        # pattern) or use the native format.
        ckptr.save(os.path.abspath(path), tree, force=True)


def load_orbax(path: str, template: Any) -> Any:
    """Inverse of save_orbax; `template` supplies structure/shapes/
    dtypes (abstract leaves are fine) exactly like load_pytree."""
    import orbax.checkpoint as ocp

    def spec(a):
        # Abstract leaves (ShapeDtypeStruct, jax.eval_shape results)
        # already carry shape/dtype; only genuine values need asarray.
        # Template shardings pass through — restoring onto a different
        # topology must honor the CALLER's shardings, not whatever the
        # file recorded (same contract as restore_sharded) — EXCEPT
        # default-single-device shardings, which must restore
        # uncommitted: a committed scalar makes the next jit reject it
        # alongside multi-device params (same special case as
        # restore_sharded above).
        sharding = getattr(a, "sharding", None)
        if isinstance(
            sharding, SingleDeviceSharding
        ) and sharding.device_set == {jax.local_devices()[0]}:
            sharding = None
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            return jax.ShapeDtypeStruct(
                tuple(a.shape), a.dtype, sharding=sharding
            )
        arr = jnp.asarray(a)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype, sharding=sharding)

    specs = jax.tree_util.tree_map(spec, template)
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.abspath(path), specs)
    # orbax returns every leaf committed; when the tree mixes
    # multi-device params with default-device scalars, the committed
    # scalars would make the next jit raise 'incompatible devices'.
    # Rewrap just the default-device leaves (host round trip only for
    # those, typically step counters) — noop for uniform trees.
    leaves = jax.tree_util.tree_leaves(specs)
    has_multi = any(
        getattr(s, "sharding", None) is not None
        and len(s.sharding.device_set) > 1
        for s in leaves
    )
    if not has_multi:
        return restored

    def uncommit(s, v):
        if getattr(s, "sharding", None) is None:
            return jnp.asarray(np.asarray(v))
        return v

    return jax.tree_util.tree_map(uncommit, specs, restored)
