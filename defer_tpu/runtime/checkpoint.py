"""Checkpoint save/resume over the native codec.

The reference has no checkpointing at all — weights flow dispatcher ->
node once at startup (reference src/dispatcher.py:60-63, src/node.py:
68-70) and are lost with the process. Here params (any nested
str-keyed dict of arrays: GraphParams, SpmdBert params, train states'
param trees) serialize to a single self-describing file, each array
compressed through the runtime codec (defer_tpu/runtime/codec.py) —
the same seam the reference runs its ZFP+LZ4 pipe through.

bfloat16 (the TPU compute dtype, which numpy lacks) ships as a uint16
byte view with its logical dtype recorded in the manifest.

File: magic line, 8-byte LE manifest length, JSON manifest
[{key, dtype, frame_len}...], then the codec frames back-to-back.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from defer_tpu.runtime import codec

_MAGIC = b"DEFERTPU-CKPT-v1\n"
_SEP = "/"


def _flatten(tree: Mapping[str, Any], prefix: str = "") -> list[tuple[str, Any]]:
    out: list[tuple[str, Any]] = []
    for k in sorted(tree):
        if _SEP in k:
            raise ValueError(f"checkpoint keys may not contain {_SEP!r}: {k!r}")
        v = tree[k]
        path = f"{prefix}{k}"
        if isinstance(v, Mapping):
            out.extend(_flatten(v, f"{path}{_SEP}"))
        else:
            out.append((path, v))
    return out


def _unflatten(items: list[tuple[str, Any]]) -> dict:
    root: dict = {}
    for path, v in items:
        parts = path.split(_SEP)
        cur = root
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    return root


def save_checkpoint(path: str, params: Mapping[str, Any], *, level: int = 3) -> None:
    """Atomically write `params` to `path` (write temp + rename)."""
    entries = []
    frames = []
    for key, value in _flatten(params):
        arr = np.asarray(value)
        logical = arr.dtype.name
        if logical == "bfloat16":
            arr = arr.view(np.uint16)
        frame = codec.encode(arr, level=level)
        entries.append({"key": key, "dtype": logical, "frame_len": len(frame)})
        frames.append(frame)
    manifest = json.dumps(entries).encode()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<q", len(manifest)))
        f.write(manifest)
        for frame in frames:
            f.write(frame)
    os.replace(tmp, path)


def load_checkpoint(path: str) -> dict:
    """Read a checkpoint back into a nested dict of jnp arrays."""
    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path!r} is not a defer_tpu checkpoint")
        (mlen,) = struct.unpack("<q", f.read(8))
        entries = json.loads(f.read(mlen).decode())
        items = []
        for e in entries:
            arr = codec.decode(f.read(e["frame_len"]))
            if e["dtype"] == "bfloat16":
                arr = arr.view(jnp.bfloat16.dtype)
            value = jnp.asarray(arr)
            items.append((e["key"], value))
    return _unflatten(items)


# -- arbitrary pytrees (train states: params + optimizer + step) -----------


def save_pytree(path: str, tree: Any, *, level: int = 3) -> None:
    """Checkpoint any pytree (e.g. a TrainState: params dict + optax
    opt_state NamedTuples + step counter) by flattening to leaves.

    The tree *structure* is not serialized — restore requires a
    template with the same structure (`load_pytree`), which every
    training setup can rebuild via its init function. This is the
    standard resume pattern and keeps the on-disk format plain arrays.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    save_checkpoint(
        path,
        {"__leaves__": {str(i): leaf for i, leaf in enumerate(leaves)}},
        level=level,
    )


def load_pytree(path: str, template: Any) -> Any:
    """Restore a pytree saved by save_pytree into `template`'s
    structure (values of `template` are ignored; shapes/dtypes of the
    stored leaves win). Raises if the leaf count doesn't match."""
    stored = load_checkpoint(path)["__leaves__"]
    treedef = jax.tree_util.tree_structure(template)
    if treedef.num_leaves != len(stored):
        raise ValueError(
            f"checkpoint has {len(stored)} leaves but the template "
            f"structure expects {treedef.num_leaves}"
        )
    leaves = [stored[str(i)] for i in range(len(stored))]
    return jax.tree_util.tree_unflatten(treedef, leaves)
