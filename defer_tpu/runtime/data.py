"""Host input pipeline: preprocessing + device prefetch.

The reference's drivers load and preprocess one image with PIL on the
host and re-feed the same tensor forever (reference src/test.py:13-16,
src/local_infer.py:10-14). Here the host side of the feed is a real
component:

  * `imagenet_preprocess` — the zoo models' input transform (resize,
    center-crop, scale) on host numpy arrays, batched.
  * `batched` — group an example stream into fixed-size batches (the
    pipeline needs static shapes; a short tail batch is dropped by
    default, XLA would otherwise recompile).
  * `prefetch_to_device` — a bounded background thread that stages
    `device_put` ahead of consumption, overlapping host→device
    transfer with device compute (the reference's decoupled feed
    thread, reference src/dispatcher.py:99-103, minus the socket).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Sequence

import jax
import numpy as np

from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)


def imagenet_preprocess(
    images: np.ndarray,
    *,
    size: int = 224,
    mode: str = "scale",
    out_dtype: Any = None,
) -> np.ndarray:
    """uint8/float HWC (or NHWC) images -> float32 NHWC model input
    (or `out_dtype`, e.g. ml_dtypes.bfloat16 — casting on the host
    halves the host->device transfer and removes the per-microbatch
    fp32->bf16 cast pass on device).

    mode="scale": x/127.5 - 1 (the MobileNet/Inception family
    convention). mode="caffe": BGR mean subtraction (ResNet50/VGG
    Keras weights convention). mode="unit": x/255 (EfficientNet — the
    real Keras model's Rescaling head, whose un-adapted Normalization
    is identity; the native zoo graph expects this done on the host).
    """
    x = np.asarray(images)
    if x.ndim == 3:
        x = x[None]
    if x.ndim != 4:
        raise ValueError(f"expected HWC or NHWC images, got shape {x.shape}")
    if x.dtype == np.uint8:
        # Fast path: the fused native C++ pass (resize+crop+affine in
        # one multithreaded sweep, defer_tpu/native/imageproc.cpp).
        from defer_tpu.runtime.native_image import native_preprocess

        out = native_preprocess(x, size=size, mode=mode, out_dtype=out_dtype)
        if out is not None:
            return out
    x = x.astype(np.float32)
    if x.shape[1] != size or x.shape[2] != size:
        x = _resize_center_crop(x, size)
    if mode == "scale":
        x = x / 127.5 - 1.0
    elif mode == "unit":
        x = x / 255.0
    elif mode == "caffe":
        # RGB -> BGR, subtract ImageNet channel means.
        x = x[..., ::-1] - np.array([103.939, 116.779, 123.68], np.float32)
    else:
        raise ValueError(f"unknown preprocess mode {mode!r}")
    return x.astype(out_dtype) if out_dtype is not None else x


def _resize_center_crop(x: np.ndarray, size: int) -> np.ndarray:
    """Resize the short side to `size`, then center-crop to size x size.

    Pure-numpy bilinear: host preprocessing must not touch the
    accelerator the pipeline runs on, and a jit-based resize would
    recompile for every distinct source (h, w) in a real image stream.
    """
    n, h, w, c = x.shape
    scale = size / min(h, w)
    nh, nw = max(size, round(h * scale)), max(size, round(w * scale))
    resized = _bilinear_resize_np(x, nh, nw)
    top, left = (nh - size) // 2, (nw - size) // 2
    return resized[:, top : top + size, left : left + size, :]


def _bilinear_resize_np(x: np.ndarray, nh: int, nw: int) -> np.ndarray:
    """Vectorized half-pixel-centered bilinear resize, NHWC."""
    n, h, w, c = x.shape
    # Sample coordinates in source space (align half-pixel centers,
    # matching jax.image.resize / TF2 'bilinear' semantics).
    ys = np.clip((np.arange(nh) + 0.5) * h / nh - 0.5, 0, h - 1)
    xs = np.clip((np.arange(nw) + 0.5) * w / nw - 0.5, 0, w - 1)
    y0 = np.floor(ys).astype(np.int64)
    x0 = np.floor(xs).astype(np.int64)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0).astype(x.dtype)[None, :, None, None]
    wx = (xs - x0).astype(x.dtype)[None, None, :, None]
    top = x[:, y0][:, :, x0] * (1 - wx) + x[:, y0][:, :, x1] * wx
    bot = x[:, y1][:, :, x0] * (1 - wx) + x[:, y1][:, :, x1] * wx
    return top * (1 - wy) + bot * wy


# Keras-weights input conventions per zoo family (reference models use
# the preprocessing their checkpoints were trained with).
_CAFFE_MODELS = ("resnet50", "resnet101", "resnet152", "vgg16", "vgg19")


def preprocess_mode(model_name: str) -> str:
    """Which imagenet_preprocess mode a zoo model's weights expect."""
    if model_name in _CAFFE_MODELS:
        return "caffe"
    if model_name.startswith("efficientnet"):
        return "unit"  # Rescaling(1/255) lives in the real Keras model
    return "scale"


def load_image_dir(
    path: str,
    *,
    extensions: Sequence[str] = (".png", ".jpg", ".jpeg", ".bmp"),
    with_names: bool = False,
) -> Iterator[Any]:
    """Decode every image in a directory (sorted order) to uint8 HWC
    RGB numpy arrays — the reference's PIL input path (reference
    src/test.py:13-16) as a stream instead of one hard-coded file.
    with_names=True yields (filename, array) pairs instead."""
    import os

    from PIL import Image

    names = sorted(
        f for f in os.listdir(path)
        if os.path.splitext(f)[1].lower() in extensions
    )
    if not names:
        raise FileNotFoundError(f"no images with {extensions} under {path!r}")
    for name in names:
        with Image.open(os.path.join(path, name)) as im:
            arr = np.asarray(im.convert("RGB"))
        yield (name, arr) if with_names else arr


def batched(
    examples: Iterable[np.ndarray],
    batch_size: int,
    *,
    drop_remainder: bool = True,
) -> Iterator[np.ndarray]:
    """Stack an example stream into fixed-size batches (static shapes —
    a ragged tail batch would force an XLA recompile)."""
    buf: list[np.ndarray] = []
    for ex in examples:
        buf.append(np.asarray(ex))
        if len(buf) == batch_size:
            yield np.stack(buf)
            buf = []
    if buf and not drop_remainder:
        yield np.stack(buf)
    elif buf:
        log.info("batched: dropped %d-example tail batch", len(buf))


_STOP = object()


def prefetch_to_device(
    it: Iterable[Any],
    device: jax.Device | None = None,
    *,
    depth: int = 2,
) -> Iterator[jax.Array]:
    """Iterate `it`, staging device_put `depth` items ahead in a
    background thread. Exceptions from the source iterator re-raise at
    the consumption point; the thread always terminates."""
    dev = device or jax.devices()[0]
    q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
    abandoned = threading.Event()

    def _put(item: Any) -> bool:
        """put that gives up when the consumer is gone, so the feeder
        thread (and the source iterator + staged device buffers it
        holds) always terminates."""
        while not abandoned.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def feed() -> None:
        try:
            for item in it:
                if not _put(jax.device_put(item, dev)):
                    return
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            _put(("__error__", e))
            return
        _put(_STOP)

    t = threading.Thread(target=feed, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _STOP:
                return
            if (
                isinstance(item, tuple)
                and len(item) == 2
                and item[0] == "__error__"
            ):
                raise item[1]
            yield item
    finally:
        # Runs on normal exhaustion, consumer error, or GeneratorExit
        # (abandoned partial read) — unblocks the feeder either way.
        abandoned.set()
