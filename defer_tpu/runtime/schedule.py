"""Mixed-mode prefill scheduling for the paged server.

`PagedDecodeServer(prefill_budget=N)` stops serializing admission
prefill against decode: instead of running a seated prompt to
completion in its own dispatches while every live slot stalls, the
server gives the prompt a SEAT whose `pos` advances chunk by chunk,
and each decode dispatch carries the live decode rows PLUS up to N
prompt tokens from the seated prefills, fused into one jitted
multi-token forward (runtime/paged.py::_tick_mixed over the _mt_body
program). This module owns the host-side planning half:

- `PrefillSeat` — one partially-prefilled request's progress: the
  suffix tokens still to run, the absolute position of the next row,
  and the radix `keep_from` boundary below which writes redirect to
  trash block 0 (hit blocks are other requests' memory).
- `plan_mixed_tick` — one tick's token plan. Decode rows come first
  (they always advance exactly one token; the plan never touches
  them), then prompt chunks are assigned to seats in admission order
  until the per-tick `budget` runs out. Every assignment is clamped
  by `chunk_cap` (the compile-shape bound, `prefill_chunk` when set)
  and by `t_limit`, the batch-wide bound on the fused program's T:
  the gathered path's contiguous-lane write spans positions
  [pos, pos+T) for EVERY row, so T must satisfy
  max(pos over live rows) + T <= MB * block_size or a clamped write
  would shift a live row (the same invariant submit()'s spec_k
  headroom and _prefill_paged's tail cap protect).

The returned T is pow2-bucketed (then clamped to `t_limit`) so the
fused program traces a small, stable shape set — the trace sanitizer
pins zero post-warmup retraces over the steady-state mix.

Seats are admitted from a bounded-lookahead window: the server caps
concurrently-prefilling seats at `prefill_lookahead`, so one giant
prompt cannot monopolize the budget N ways and admission order stays
near-FIFO.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["PrefillSeat", "plan_mixed_tick"]


@dataclasses.dataclass
class PrefillSeat:
    """One admitted-but-still-prefilling request's chunk progress.

    `tokens` is the suffix actually scheduled — a radix admit walks
    its leading full blocks first and schedules only the non-shared
    tail (at least one token: the last prompt position must run so
    its logits exist to seed the first generated token). `base` is
    the absolute position of tokens[0] (global prefix length, or the
    radix reuse point); `keep_from` the boundary below which the
    fused program's writes redirect to trash block 0."""

    rid: int
    tokens: np.ndarray  # [ts] int32 suffix token ids still to run
    base: int  # absolute position of tokens[0]
    keep_from: int  # writes below this absolute position -> trash
    done: int = 0  # tokens already landed

    def __post_init__(self) -> None:
        self.tokens = np.asarray(self.tokens, np.int32).reshape(-1)
        if self.tokens.size < 1:
            raise ValueError(
                "a prefill seat needs at least one token to run (the "
                "last prompt position seeds the first generated token)"
            )

    @property
    def remaining(self) -> int:
        return int(self.tokens.size) - self.done

    @property
    def pos(self) -> int:
        """Absolute position of the NEXT row this seat will write."""
        return self.base + self.done

    @property
    def finished(self) -> bool:
        return self.done >= int(self.tokens.size)

    def take(self, n: int) -> np.ndarray:
        """Consume the next `n` scheduled tokens (the tick's chunk)."""
        if not 1 <= n <= self.remaining:
            raise ValueError(
                f"seat rid={self.rid} asked for {n} of "
                f"{self.remaining} remaining tokens"
            )
        chunk = self.tokens[self.done : self.done + n]
        self.done += n
        return chunk


def pow2_bucket(n: int, cap: int) -> int:
    """Round `n` up to a power of two, clamped to [1, cap] — the
    compile-shape discipline every multi-token paged dispatch follows
    (prefill tails, ingest lanes, and now mixed ticks)."""
    if n < 1:
        n = 1
    t = 1 << (n - 1).bit_length()
    return max(1, min(t, cap))


def plan_mixed_tick(
    remaining: list[int],
    budget: int,
    chunk_cap: int,
    t_limit: int,
) -> tuple[int, list[int]]:
    """Plan one mixed tick's prompt-token assignments.

    `remaining[j]` is seat j's unfinished suffix length, in admission
    order. Returns `(T, ns)`: `ns[j]` prompt tokens for seat j this
    tick (0 = the seat idles behind the budget), and `T` the fused
    program's per-row token count — pow2-bucketed over the largest
    assignment and clamped to `t_limit` (never below 1: decode rows
    always ride at T >= 1).

    Decode rows are implicit: they are not planned, never preempted,
    and always advance exactly one token — the budget only rations
    the EXTRA prompt tokens a tick carries.
    """
    if budget < 1:
        raise ValueError(f"budget must be >= 1, got {budget}")
    if chunk_cap < 1:
        raise ValueError(f"chunk_cap must be >= 1, got {chunk_cap}")
    if t_limit < 1:
        raise ValueError(f"t_limit must be >= 1, got {t_limit}")
    left = budget
    ns: list[int] = []
    for rem in remaining:
        if rem < 0:
            raise ValueError(f"negative remaining {rem}")
        n = min(rem, left, chunk_cap, t_limit)
        ns.append(max(n, 0))
        left -= max(n, 0)
    top = max(ns, default=0)
    return pow2_bucket(top, t_limit), ns
