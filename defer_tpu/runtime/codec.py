"""Array transfer codec — the reference's ZFP+LZ4 seam, TPU-native.

The reference compresses every activation and weight hop
(`_comp`/`_decomp`, reference src/dispatcher.py:89-92 and
src/node.py:93-96) because its transport is Ethernet. On TPU, ICI
transfers need no codec (XLA collectives own that path); this seam
exists for the host/DCN side — checkpoint shipping, multi-slice
activation relay, dispatcher→host weight distribution.

Two backends, one wire format:

  * native: `defer_tpu/native/codec.cpp` (byteshuffle + zstd), built
    on demand with g++ and loaded via ctypes — the C++ analogue of the
    reference's zfpy/liblz4 C dependencies.
  * fallback: numpy byteshuffle + zlib, used when the native build is
    unavailable. Same frame layout, different `scheme` tag, so either
    side can decode a stream regardless of which encoder produced it.

Frame: magic(2) ver(1) scheme(1) dtype_len(1) dtype ndim(1) dims(8 each)
then payload.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
import zlib

import numpy as np

from defer_tpu.models.quant import dequantize_symmetric, quantize_symmetric
from defer_tpu.obs.metrics import get_registry
from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)

# Raw-in vs frame-out byte totals; their ratio is the codec's achieved
# compression over everything this process encoded.
_obs_raw = get_registry().counter(
    "defer_codec_raw_bytes_total", "Uncompressed bytes handed to encode()"
)
_obs_encoded = get_registry().counter(
    "defer_codec_encoded_bytes_total", "Frame bytes produced by encode()"
)

_MAGIC = b"DC"
_VERSION = 1
SCHEME_RAW = 0  # passthrough (level=0): fast links where codec loses
SCHEME_ZSTD_SHUFFLE = 1  # native codec
SCHEME_ZLIB_SHUFFLE = 2  # pure-python fallback
SCHEME_Q8 = 3  # lossy: symmetric int8 quantization, then 0/1/2 inside

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "native")
_SRC = os.path.abspath(os.path.join(_NATIVE_DIR, "codec.cpp"))
_SO = os.path.abspath(os.path.join(_NATIVE_DIR, "libdefercodec.so"))

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_tried = False


def _build_native() -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _SO, "-lzstd",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native codec build failed to run: %s", e)
        return False
    if proc.returncode != 0:
        log.warning("native codec build failed:\n%s", proc.stderr[-2000:])
        return False
    return True


def load_native():
    """Build (if needed) and load the native codec; None if unavailable."""
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if not os.path.exists(_SO) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        ):
            if not _build_native():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warning("native codec load failed: %s", e)
            return None
        lib.defer_codec_bound.restype = ctypes.c_size_t
        lib.defer_codec_bound.argtypes = [ctypes.c_size_t]
        lib.defer_codec_encode.restype = ctypes.c_size_t
        lib.defer_codec_encode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.defer_codec_decode.restype = ctypes.c_size_t
        lib.defer_codec_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p,
            ctypes.c_size_t, ctypes.c_size_t,
        ]
        _lib = lib
        return _lib


def _shuffle_np(raw: bytes, elem: int) -> bytes:
    a = np.frombuffer(raw, np.uint8).reshape(-1, elem)
    return np.ascontiguousarray(a.T).tobytes()


def _unshuffle_np(raw: bytes, elem: int) -> bytes:
    a = np.frombuffer(raw, np.uint8).reshape(elem, -1)
    return np.ascontiguousarray(a.T).tobytes()


def encode(
    arr: np.ndarray,
    *,
    level: int = 3,
    quantize: str | None = None,
    _count: bool = True,
) -> bytes:
    """Array -> self-describing compressed frame. level=0 skips
    compression entirely (raw passthrough for links where the codec
    costs more than the bytes it saves).

    quantize="int8" (floating-point arrays only) is the LOSSY
    quantize-for-transfer mode the reference approximates with ZFP's
    fixed-precision modes: symmetric per-tensor int8 with an fp64
    scale, ~4x fewer bytes before entropy coding, max abs error =
    amax/127 ~ 0.8% of the dynamic range. The inner int8 payload still
    goes through the lossless pipeline, so either backend decodes it;
    decode() restores the ORIGINAL dtype."""
    if quantize is not None:
        if quantize != "int8":
            raise ValueError(f"unknown quantize mode {quantize!r}")
        arr = np.ascontiguousarray(arr)
        if not np.issubdtype(arr.dtype, np.floating):
            raise ValueError(
                f"quantize='int8' needs a floating dtype, got {arr.dtype}"
            )
        a64 = arr.astype(np.float64)
        amax = float(np.max(np.abs(a64))) if arr.size else 0.0
        if not np.isfinite(amax):
            # A single NaN/Inf would silently corrupt the WHOLE tensor
            # (scale=inf zeroes everything; NaN->int8 is undefined).
            # The lossless path preserves non-finite values — use it.
            raise ValueError(
                "quantize='int8' requires finite values; tensor contains "
                "NaN/Inf — send it losslessly instead"
            )
        # Per-tensor symmetric int8 through the ONE shared convention
        # (models/quant.py): s = amax/127, with degenerate scales
        # (zero tensor, or amax/127 underflowing to 0.0) clamped to
        # 1.0 so subnormal inputs don't become clipped +/-127 garbage.
        q, s = quantize_symmetric(a64, axis=None, xp=np)
        scale = float(s)
        # _count=False: the inner int8 frame is an implementation
        # detail of THIS encode — letting it count would double-book
        # the raw bytes and understate the compression ratio.
        inner = encode(q, level=level, _count=False)
        dtype = arr.dtype.str.encode()
        header = struct.pack(
            f"<2sBBB{len(dtype)}sB", _MAGIC, _VERSION, SCHEME_Q8,
            len(dtype), dtype, 0,
        )
        frame = header + struct.pack("<d", scale) + inner
        if _count:
            _obs_raw.inc(arr.nbytes)
            _obs_encoded.inc(len(frame))
        return frame

    arr = np.ascontiguousarray(arr)
    raw = arr.tobytes()
    elem = arr.dtype.itemsize
    dtype = arr.dtype.str.encode()

    payload = None
    scheme = SCHEME_ZLIB_SHUFFLE
    if level == 0:
        payload, scheme = raw, SCHEME_RAW
    lib = load_native() if payload is None else None
    if lib is not None and raw:
        cap = lib.defer_codec_bound(len(raw))
        dst = ctypes.create_string_buffer(cap)
        n = lib.defer_codec_encode(raw, len(raw), elem, level, dst, cap)
        if n:
            # string_at copies only the n compressed bytes (dst.raw[:n]
            # would materialize the whole bound-sized buffer first).
            payload = ctypes.string_at(dst, n)
            scheme = SCHEME_ZSTD_SHUFFLE
        else:
            log.warning("native codec encode failed; using fallback")
    if payload is None:
        shuffled = _shuffle_np(raw, elem) if elem > 1 and raw else raw
        # zstd levels run to 22; clamp for zlib's 0-9 range.
        payload = zlib.compress(shuffled, min(level, 9))

    header = struct.pack(
        f"<2sBBB{len(dtype)}sB{arr.ndim}q",
        _MAGIC, _VERSION, scheme, len(dtype), dtype, arr.ndim, *arr.shape,
    )
    frame = header + payload
    if _count:
        _obs_raw.inc(arr.nbytes)
        _obs_encoded.inc(len(frame))
    return frame


def decode(frame: bytes) -> np.ndarray:
    """Compressed frame -> array (either scheme, either backend)."""
    if frame[:2] != _MAGIC:
        raise ValueError("not a defer_tpu codec frame")
    ver, scheme, dlen = struct.unpack_from("<BBB", frame, 2)
    if ver != _VERSION:
        raise ValueError(f"unsupported codec frame version {ver}")
    off = 5
    dtype = np.dtype(frame[off : off + dlen].decode())
    off += dlen
    (ndim,) = struct.unpack_from("<B", frame, off)
    off += 1
    shape = struct.unpack_from(f"<{ndim}q", frame, off)
    off += 8 * ndim
    payload = frame[off:]
    if scheme == SCHEME_Q8:
        (scale,) = struct.unpack_from("<d", payload, 0)
        q = decode(payload[8:])
        return dequantize_symmetric(q, scale, np.float64, xp=np).astype(
            dtype
        )
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if ndim else dtype.itemsize
    nbytes = max(nbytes, 0)
    elem = dtype.itemsize

    if scheme == SCHEME_RAW:
        if len(payload) != nbytes:
            raise ValueError("corrupt raw codec frame")
        raw = payload
    elif scheme == SCHEME_ZSTD_SHUFFLE:
        lib = load_native()
        if lib is None:
            raise RuntimeError(
                "frame was encoded with the native zstd codec but the "
                "native library is unavailable on this host"
            )
        dst = ctypes.create_string_buffer(nbytes) if nbytes else b""
        if nbytes:
            n = lib.defer_codec_decode(payload, len(payload), dst, nbytes, elem)
            if n != nbytes:
                raise ValueError("corrupt native codec frame")
            raw = dst.raw
        else:
            raw = b""
    elif scheme == SCHEME_ZLIB_SHUFFLE:
        shuffled = zlib.decompress(payload)
        if len(shuffled) != nbytes:
            raise ValueError("corrupt zlib codec frame")
        raw = _unshuffle_np(shuffled, elem) if elem > 1 and nbytes else shuffled
    else:
        raise ValueError(f"unknown codec scheme {scheme}")
    return np.frombuffer(raw, dtype).reshape(shape).copy()
