"""ctypes loader for the native host image preprocessor.

`defer_tpu/native/imageproc.cpp` fuses bilinear resize + center crop +
per-channel affine into one multithreaded C++ pass (the native
data-loader component; the reference leans on PIL/numpy on the driver,
reference src/test.py:13-16). `imagenet_preprocess` in
defer_tpu/runtime/data.py uses it transparently for uint8 input and
falls back to the numpy path when the native build is unavailable —
both produce the same values (tested to ~1e-3 absolute).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from defer_tpu.utils.logging import get_logger

log = get_logger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "native")
_SRC = os.path.abspath(os.path.join(_NATIVE_DIR, "imageproc.cpp"))
_SO = os.path.abspath(os.path.join(_NATIVE_DIR, "libdeferimage.so"))

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_tried = False

# mode -> (scale, per-OUTPUT-channel offsets, swap_rb)
_MODES: dict[str, tuple[float, tuple[float, float, float], int]] = {
    "scale": (1.0 / 127.5, (-1.0, -1.0, -1.0), 0),
    "unit": (1.0 / 255.0, (0.0, 0.0, 0.0), 0),
    "caffe": (1.0, (-103.939, -116.779, -123.68), 1),
}


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _SO, "-pthread",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native imageproc build failed to run: %s", e)
        return False
    if proc.returncode != 0:
        log.warning("native imageproc build failed:\n%s", proc.stderr[-2000:])
        return False
    return True


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        stale = not os.path.exists(_SO) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        )
        if stale and not _build() and not os.path.exists(_SO):
            # No compiler AND no prebuilt library — numpy fallback.
            # (A rebuild failure with an existing .so still loads it:
            # git does not preserve mtimes, so a fresh clone often
            # looks 'stale' on hosts without g++.)
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warning("native imageproc load failed: %s", e)
            return None
        lib.defer_preprocess.restype = ctypes.c_int
        lib.defer_preprocess.argtypes = [
            ctypes.c_void_p,  # src
            ctypes.c_int64,  # n
            ctypes.c_int64,  # h
            ctypes.c_int64,  # w
            ctypes.c_int64,  # c
            ctypes.c_int64,  # size
            ctypes.POINTER(ctypes.c_float),  # scale
            ctypes.POINTER(ctypes.c_float),  # offset
            ctypes.c_int,  # swap_rb
            ctypes.c_int,  # out_bf16
            ctypes.c_int64,  # num_threads
            ctypes.c_void_p,  # dst
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _is_bf16(dtype) -> bool:
    try:
        import ml_dtypes

        return np.dtype(dtype) == np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover
        return False


def native_preprocess(
    images: np.ndarray,
    *,
    size: int,
    mode: str,
    out_dtype=None,
    num_threads: int | None = None,
) -> np.ndarray | None:
    """Fused resize+crop+affine via the C++ library.

    Returns None when the native path cannot handle the request (no
    library, non-uint8 input, unknown mode, unsupported out_dtype) —
    the caller falls back to numpy.
    """
    if mode not in _MODES:
        return None
    x = np.asarray(images)
    if x.ndim == 3:
        x = x[None]
    if x.ndim != 4 or x.dtype != np.uint8 or x.shape[-1] != 3:
        return None
    out_dtype = np.float32 if out_dtype is None else out_dtype
    bf16 = _is_bf16(out_dtype)
    if not bf16 and np.dtype(out_dtype) != np.dtype(np.float32):
        return None
    lib = _load()
    if lib is None:
        return None

    x = np.ascontiguousarray(x)
    n, h, w, c = x.shape
    scale_v, offsets, swap = _MODES[mode]
    scale_arr = (ctypes.c_float * c)(*([scale_v] * c))
    offset_arr = (ctypes.c_float * c)(*offsets)
    out = np.empty((n, size, size, c), dtype=out_dtype)
    if num_threads is None:
        num_threads = max(1, (os.cpu_count() or 2) // 2)
    rc = lib.defer_preprocess(
        x.ctypes.data_as(ctypes.c_void_p),
        n,
        h,
        w,
        c,
        size,
        scale_arr,
        offset_arr,
        swap,
        1 if bf16 else 0,
        num_threads,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    if rc != 0:
        log.warning("native preprocess returned rc=%d; falling back", rc)
        return None
    return out
