"""Paged KV cache: a shared block pool instead of per-slot max_len
lanes (the vLLM idea, TPU-shaped).

A contiguous continuous-batching cache (runtime/decode_server.py)
reserves `max_batch x max_len` K/V rows even when every request is
short — decode HBM is cache-bound, so reserved-but-unused rows are the
serving memory ceiling. Here the cache is a pool of fixed-size BLOCKS
([L, num_blocks, H_kv, block_size, Dh]); each slot holds a BLOCK TABLE
of pool indices, and memory scales with the sum of actual request
budgets, not slots x max_len.

Static-shape design (everything jits once):

  * the decode step gathers each slot's blocks into the standard
    contiguous [B, H_kv, S, Dh] view (one gather per layer) and runs
    the EXACT SAME block math as the flat decoder (GptDecoder._block)
    — numerical parity is inherited, not re-proven — then scatters the
    single new K/V row back to its block;
  * block tables are a fixed [B, max_blocks] shape; unallocated
    entries point at the reserved TRASH block 0 (never allocated to a
    request), so out-of-budget writes land in scrap instead of another
    request's memory and garbage reads sit beyond the position mask;
  * allocation is host-side and exact: a request's block need is known
    at submit time (prompt + step budget, eos can only shorten it), so
    admission takes ceil(total/block_size) blocks from the free list
    and finishing returns them — when the pool is exhausted, requests
    simply wait (the pool, not the slot count, is the admission
    limit).

Prefill reuses the flat decoder's admission path (single-request
contiguous prefill), and the resulting rows are scattered into the
allocated blocks in one jitted op.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class PagedDecodeServer:
    """Greedy continuous batching over a paged KV pool.

    Protocol-compatible with runtime/decode_server.DecodeServer
    (submit -> run -> {rid: ids}), with the pool replacing per-slot
    max_len lanes. `num_blocks` INCLUDES the reserved trash block 0.
    """

    def __init__(
        self,
        dec: Any,
        params: dict,
        *,
        num_blocks: int,
        block_size: int = 16,
        max_batch: int = 4,
        eos_id: int | None = None,
        on_token: Any = None,
    ):
        """`on_token(request_id, token_id, done)` — optional streaming
        callback, same contract as the flat server's."""
        if getattr(dec, "rolling_cache", False):
            raise ValueError("paged serving does not support rolling caches")
        # Multi-LoRA: adapter banks (parallel/lora.py::stack_adapters)
        # make the slot -> adapter assignment per-slot state, same as
        # the flat server; id 0 = base model.
        from defer_tpu.parallel.lora import adapter_bank_info

        n_adapters = adapter_bank_info(params)
        self.multi_lora = n_adapters is not None
        if self.multi_lora:
            self.num_adapters = n_adapters
        if block_size < 1 or num_blocks < 2:
            raise ValueError(
                f"need block_size >= 1 and num_blocks >= 2 (one trash "
                f"block + one usable), got {block_size}/{num_blocks}"
            )
        self.dec = dec
        self.params = params
        self.B = max_batch
        self.bs = block_size
        self.eos_id = eos_id
        self.on_token = on_token
        cfg = dec.cfg
        # Max logical blocks any sequence can span.
        self.MB = -(-cfg.max_len // block_size)
        dh = cfg.dim // cfg.num_heads
        pool_shape = (
            cfg.num_layers, num_blocks, cfg.kv_heads, block_size, dh,
        )
        self.pool_k = jnp.zeros(pool_shape, dec.compute_dtype)
        self.pool_v = jnp.zeros(pool_shape, dec.compute_dtype)
        # Block 0 is trash: unallocated table entries point at it.
        self.free = list(range(1, num_blocks))
        self.tables = np.zeros((max_batch, self.MB), np.int32)
        self.pos = np.zeros((max_batch,), np.int32)
        self.adapter = np.zeros((max_batch,), np.int32)
        self.slots: list[dict | None] = [None] * max_batch
        self.pending: list[tuple[int, jax.Array, int, int]] = []
        self.done: dict[int, jax.Array] = {}
        self._next_id = 0
        self.ticks = 0
        self.blocks_peak = 0
        self._step = None
        self._insert = None

    # -- public API -------------------------------------------------------

    def submit(
        self,
        prompt_ids: jax.Array,
        num_steps: int,
        *,
        adapter_id: int = 0,
    ) -> int:
        if prompt_ids.ndim != 2 or prompt_ids.shape[0] != 1:
            raise ValueError("submit one request at a time ([1, T])")
        if adapter_id:
            if not self.multi_lora:
                raise ValueError(
                    "adapter_id set but params carry no adapter banks "
                    "(parallel/lora.py::stack_adapters)"
                )
            if not 0 <= adapter_id < self.num_adapters:
                raise ValueError(
                    f"adapter_id {adapter_id} out of range "
                    f"[0, {self.num_adapters})"
                )
        t0 = prompt_ids.shape[1]
        if t0 < 1 or num_steps < 1:
            raise ValueError("need at least 1 prompt token and 1 step")
        if t0 + num_steps > self.dec.cfg.max_len:
            raise ValueError(
                f"prompt {t0} + steps {num_steps} exceeds max_len "
                f"{self.dec.cfg.max_len}"
            )
        need = -(-(t0 + num_steps) // self.bs)
        if need > self.pool_k.shape[1] - 1:
            # Not even an empty pool could hold it — waiting would
            # deadlock the queue.
            raise ValueError(
                f"request needs {need} blocks but the pool has "
                f"{self.pool_k.shape[1] - 1} usable"
            )
        rid = self._next_id
        self._next_id += 1
        self.pending.append((rid, prompt_ids, num_steps, adapter_id))
        return rid

    def run(self) -> dict[int, jax.Array]:
        while self.pending or any(self.slots):
            self._admit()
            self._tick()
        return self.done

    @property
    def blocks_in_use(self) -> int:
        return sum(len(s["blocks"]) for s in self.slots if s)

    # -- internals --------------------------------------------------------

    def _build(self):
        if self._step is not None:
            return
        # Memoized ON THE DECODER (utils/memo.py): jit's cache is keyed
        # on the function object, so per-server closures would re-trace
        # and re-compile on every new server over the same decoder
        # (e.g. back-to-back bench runs).
        from defer_tpu.utils.memo import cached_step

        self._step = cached_step(
            self.dec, ("paged_step", self.bs), self._build_step
        )
        self._insert = cached_step(
            self.dec, ("paged_insert", self.bs), self._build_insert
        )

    def _build_step(self):
        dec, bs = self.dec, self.bs

        def step(params, pk, pv, tables, pos, ids, adapter_ids):
            b = ids.shape[0]
            x = dec._embed_tokens(params, ids, pos)
            rows = jnp.arange(b)

            def body(carry, layer):
                x = carry
                p, pk_l, pv_l = layer  # [NB, Hkv, bs, Dh]
                # Gather this slot's pages into the contiguous view
                # the flat block math expects: [B, Hkv, MB*bs, Dh].
                kc = pk_l[tables]  # [B, MB, Hkv, bs, Dh]
                vc = pv_l[tables]
                b_, mb, hkv, _, dh = kc.shape
                kc = kc.transpose(0, 2, 1, 3, 4).reshape(
                    b_, hkv, mb * bs, dh
                )
                vc = vc.transpose(0, 2, 1, 3, 4).reshape(
                    b_, hkv, mb * bs, dh
                )
                out, kc, vc = dec._block(
                    p, x, kc, vc, pos, adapter_ids=adapter_ids
                )
                # Scatter ONLY the new row back to its page.
                blk = tables[rows, pos // bs]  # [B]
                row = pos % bs
                new_k = kc[rows, :, pos, :]  # [B, Hkv, Dh]
                new_v = vc[rows, :, pos, :]
                pk_l = pk_l.at[blk, :, row, :].set(new_k)
                pv_l = pv_l.at[blk, :, row, :].set(new_v)
                return out, (pk_l, pv_l)

            x, (pk, pv) = lax.scan(
                body, x, (params["stack"], pk, pv)
            )
            logits = dec._final_logits(params, x)
            return logits, pk, pv

        return jax.jit(step, donate_argnums=(1, 2))

    def _build_insert(self):
        bs = self.bs

        def insert(pk, pv, small_k, small_v, table_row):
            """Scatter a contiguous single-request prefill cache
            ([L, 1, Hkv, S, Dh]) into this request's pool blocks.
            Rows beyond the prompt are garbage the position mask
            hides; unowned table entries point at trash block 0, so
            their writes land in scrap by the module invariant (no
            masking needed — duplicate trash writes just race over
            garbage)."""
            mb = table_row.shape[0]
            s_need = mb * bs
            k_rows = small_k[:, 0]  # [L, Hkv, S, Dh]
            v_rows = small_v[:, 0]
            pad = s_need - k_rows.shape[2]
            if pad > 0:
                k_rows = jnp.pad(
                    k_rows, ((0, 0), (0, 0), (0, pad), (0, 0))
                )
                v_rows = jnp.pad(
                    v_rows, ((0, 0), (0, 0), (0, pad), (0, 0))
                )
            else:
                k_rows = k_rows[:, :, :s_need]
                v_rows = v_rows[:, :, :s_need]
            L, hkv, _, dh = k_rows.shape
            k_blocks = k_rows.reshape(L, hkv, mb, bs, dh).transpose(
                0, 2, 1, 3, 4
            )  # [L, MB, Hkv, bs, Dh]
            v_blocks = v_rows.reshape(L, hkv, mb, bs, dh).transpose(
                0, 2, 1, 3, 4
            )
            pk = pk.at[:, table_row].set(k_blocks)
            pv = pv.at[:, table_row].set(v_blocks)
            return pk, pv

        return jax.jit(insert, donate_argnums=(0, 1))

    def _admit(self) -> None:
        for i in range(self.B):
            if self.slots[i] is not None or not self.pending:
                continue
            rid, prompt, steps, adapter_id = self.pending[0]
            t0 = prompt.shape[1]
            need = -(-(t0 + steps) // self.bs)
            if need > len(self.free):
                return  # pool exhausted: wait for a finisher
            self.pending.pop(0)
            blocks = [self.free.pop() for _ in range(need)]
            self._build()
            self.blocks_peak = max(
                self.blocks_peak, self.blocks_in_use + need
            )
            # Contiguous prefill through the flat decoder — pow2
            # bucketed like the flat server, so the compiled prefill
            # shape set stays tiny — then page the rows in.
            pad = 1 << (t0 - 1).bit_length()
            pad = min(pad, self.dec.cfg.max_len)
            padded = jnp.concatenate(
                [prompt, jnp.zeros((1, pad - t0), prompt.dtype)], axis=1
            )
            small = self.dec.init_cache(1)
            if self.multi_lora:
                small["adapter"] = jnp.full((1,), adapter_id, jnp.int32)
            logits, small = self.dec.make_step()(
                self.params, small, padded
            )
            table_row = np.zeros((self.MB,), np.int32)
            for j, blk in enumerate(blocks):
                table_row[j] = blk
            self.pool_k, self.pool_v = self._insert(
                self.pool_k,
                self.pool_v,
                small["k"],
                small["v"],
                jnp.asarray(table_row),
            )
            first = jnp.argmax(logits[:, t0 - 1, :], axis=-1)[
                :, None
            ].astype(prompt.dtype)
            self.tables[i] = table_row
            self.pos[i] = t0
            self.adapter[i] = adapter_id
            slot = {
                "rid": rid,
                "remaining": steps - 1,
                "last": first,
                "toks": [prompt, first],
                "blocks": blocks,
            }
            self.slots[i] = slot
            self._emit_token(i, slot, int(first[0, 0]))

    def _tick(self) -> None:
        live = [s is not None for s in self.slots]
        if not any(live):
            return
        self._build()
        feed = jnp.concatenate(
            [
                s["last"] if s else jnp.zeros((1, 1), jnp.int32)
                for s in self.slots
            ],
            axis=0,
        )
        # Idle slots write into trash block 0 at position 0.
        pos = jnp.asarray(
            np.where(live, self.pos, 0).astype(np.int32)
        )
        logits, self.pool_k, self.pool_v = self._step(
            self.params,
            self.pool_k,
            self.pool_v,
            jnp.asarray(self.tables),
            pos,
            feed,
            jnp.asarray(self.adapter),
        )
        self.ticks += 1
        nxt = jnp.argmax(logits[:, -1, :], axis=-1)
        # Host transfer only when eos/streaming needs the values —
        # the plain path stays async (same guard as the flat server).
        need_host = self.eos_id is not None or self.on_token is not None
        host_nxt = np.asarray(nxt) if need_host else None
        for i, slot in enumerate(self.slots):
            if slot is None:
                continue
            tok = nxt[i][None, None].astype(slot["last"].dtype)
            slot["last"] = tok
            slot["toks"].append(tok)
            slot["remaining"] -= 1
            self.pos[i] += 1
            self._emit_token(
                i, slot, int(host_nxt[i]) if host_nxt is not None else None
            )

    def _emit_token(self, i: int, slot: dict, tok: int | None) -> None:
        """Shared eos/streaming/finish bookkeeping for one emitted
        token (admission first-token and every tick): `tok` is the
        host-side token value, or None when neither eos nor streaming
        needed the transfer."""
        if (
            self.eos_id is not None
            and tok is not None
            and tok == self.eos_id
        ):
            slot["remaining"] = 0
        if self.on_token is not None:
            self.on_token(slot["rid"], tok, slot["remaining"] == 0)
        if slot["remaining"] == 0:
            self._finish(i)

    def _finish(self, i: int) -> None:
        slot = self.slots[i]
        self.done[slot["rid"]] = jnp.concatenate(slot["toks"], axis=1)
        self.free.extend(slot["blocks"])
        self.tables[i] = 0
        self.pos[i] = 0
        self.adapter[i] = 0
        self.slots[i] = None


def serve_paged(
    dec: Any,
    params: dict,
    requests: list[tuple[jax.Array, int]],
    *,
    num_blocks: int,
    block_size: int = 16,
    max_batch: int = 4,
    eos_id: int | None = None,
    adapter_ids: list | None = None,
) -> tuple[list[jax.Array], dict]:
    """One-shot paged serving; returns (outputs in submission order,
    stats incl. peak pool usage). `adapter_ids` optionally assigns a
    LoRA adapter per request (parallel/lora.py::stack_adapters)."""
    srv = PagedDecodeServer(
        dec,
        params,
        num_blocks=num_blocks,
        block_size=block_size,
        max_batch=max_batch,
        eos_id=eos_id,
    )
    aids = adapter_ids or [0] * len(requests)
    if len(aids) != len(requests):
        raise ValueError(
            f"adapter_ids has {len(aids)} entries for "
            f"{len(requests)} requests"
        )
    rids = [
        srv.submit(p, s, adapter_id=a)
        for (p, s), a in zip(requests, aids)
    ]
    done = srv.run()
    stats = {
        "ticks": srv.ticks,
        "peak_blocks": srv.blocks_peak,
        "pool_blocks": int(srv.pool_k.shape[1]) - 1,
        "block_size": block_size,
        "flat_equivalent_rows": max_batch * dec.cfg.max_len,
    }
    return [done[r] for r in rids], stats
